"""FleetRouter: the fleet must be invisible in the tokens.

The acceptance bar for multi-replica serving (docs/fleet_serving.md):
whatever the router does — affinity routing, spillover, prefill/decode
disaggregation with KV-page handoff, rolling restarts with failover —
every completion must equal its single-server lockstep row, greedy AND
sampled. The tests below pin that parity contract plus the fleet's own
bookkeeping: refcount/registry cleanliness on BOTH sides of a handoff,
one trace id per request across failover, and the aggregated
/metrics + /healthz endpoint.
"""

import json
import os
import re
import urllib.error
import urllib.request

os.environ.setdefault("PFX_PALLAS_INTERPRET", "1")

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.core.fleet import FleetReplica, FleetRouter
from paddlefleetx_tpu.core.paging import page_prefix_keys
from paddlefleetx_tpu.core.serving import GenerationServer, RequestShed
from paddlefleetx_tpu.models.gpt import GPTConfig, GPTForPretraining
from paddlefleetx_tpu.models.gpt.generation import (
    GenerationConfig, generate, left_pad_batch,
)
from paddlefleetx_tpu.observability import export
from paddlefleetx_tpu.observability import metrics
from paddlefleetx_tpu.observability import server as obs_server
from paddlefleetx_tpu.observability import timeline
from paddlefleetx_tpu.observability.recorder import read_events

CFG = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_position_embeddings=48,
                hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0)
# multi-page capacity for the disaggregation tests: prompts span >1
# 128-token page so a handoff actually moves a page list
PCFG512 = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_attention_heads=4,
                    max_position_embeddings=512,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
EOS = PAD = 95

PROMPTS = [[5, 9, 2, 7, 1], [11, 3], [4, 4, 8, 1, 2, 6, 9],
           [13, 2, 2], [1], [7, 8]]


@pytest.fixture(scope="module")
def model_and_params():
    model = GPTForPretraining(CFG)
    variables = model.init({"params": jax.random.key(0)},
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables["params"]


@pytest.fixture(scope="module")
def paged512_model_and_params():
    model = GPTForPretraining(PCFG512)
    variables = model.init({"params": jax.random.key(0)},
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables["params"]


def _greedy_cfg(max_dec=8):
    return GenerationConfig(max_dec_len=max_dec,
                            decode_strategy="greedy_search",
                            eos_token_id=EOS, pad_token_id=PAD)


def _sampling_cfg(max_dec=8):
    return GenerationConfig(max_dec_len=max_dec,
                            decode_strategy="sampling",
                            top_k=8, top_p=0.9, temperature=0.7,
                            eos_token_id=EOS, pad_token_id=PAD)


def _lockstep(model, params, prompts, gen_cfg):
    ids, mask = left_pad_batch(prompts, PAD)
    out = np.asarray(generate(model, params, jnp.asarray(ids),
                              jnp.asarray(mask), jax.random.key(0),
                              gen_cfg))
    rows = []
    for row in out:
        toks = []
        for t in row:
            toks.append(int(t))
            if int(t) == EOS:
                break
        rows.append(toks)
    return rows


def _mixed_factory(model, params, gen_cfg, **kw):
    """Identical-replica factory — the fleet's parity boundary."""
    def factory(name):
        return GenerationServer(model, params, gen_cfg, num_slots=2,
                                rng=jax.random.PRNGKey(7), **kw)
    return factory


def _drain_fleet(fleet, done):
    while fleet.busy:
        for c in fleet.step():
            done[c.request_id] = c
    return done


def _long_prompts(seed=3):
    """Multi-page prompts (2 pages each) for the handoff tests."""
    rng = np.random.default_rng(seed)
    return [rng.integers(0, EOS, n).tolist() for n in (200, 210, 220)]


# -- parity: the fleet is invisible in the tokens ----------------------


def test_fleet_parity_greedy(model_and_params):
    """A 2-replica mixed fleet serves PROMPTS token-identically to
    the single lockstep batch, whatever replica each request lands
    on."""
    model, params = model_and_params
    gen_cfg = _greedy_cfg()
    ref = _lockstep(model, params, PROMPTS, gen_cfg)
    fleet = FleetRouter(_mixed_factory(model, params, gen_cfg), 2)
    comps = fleet.run(PROMPTS)
    assert [c.tokens for c in comps] == ref
    assert all(c.finish_reason in ("eos", "length") for c in comps)
    summ = fleet.summary()
    assert summ["submitted"] == 6 and summ["shed"] == 0
    # both replicas actually served
    assert all(r["decode_tokens"] > 0 for r in summ["per_replica"])
    fleet.close()


def test_fleet_parity_sampled(model_and_params):
    """Sampled parity: router-assigned nonces in global submission
    order make the fleet reproduce a single server's draws exactly —
    the replica a request lands on must not change its stream."""
    model, params = model_and_params
    gen_cfg = _sampling_cfg()
    single = GenerationServer(model, params, gen_cfg, num_slots=6,
                              rng=jax.random.PRNGKey(7))
    ref = [c.tokens for c in single.run(PROMPTS)]
    fleet = FleetRouter(_mixed_factory(model, params, gen_cfg), 2)
    comps = fleet.run(PROMPTS)
    assert [c.tokens for c in comps] == ref
    fleet.close()


@pytest.mark.parametrize("make_cfg", [_greedy_cfg, _sampling_cfg],
                         ids=["greedy", "sampled"])
def test_fleet_failover_parity(model_and_params, make_cfg):
    """Mid-run restart of a replica: its partials fail over to the
    peer via submit(resume_tokens=..., nonce=...) and the stitched
    streams stay token-exact — zero dropped committed tokens, zero
    shed, greedy and sampled alike."""
    model, params = model_and_params
    gen_cfg = make_cfg()
    if make_cfg is _greedy_cfg:
        ref = _lockstep(model, params, PROMPTS, gen_cfg)
    else:
        single = GenerationServer(model, params, gen_cfg,
                                  num_slots=6,
                                  rng=jax.random.PRNGKey(7))
        ref = [c.tokens for c in single.run(PROMPTS)]
    fleet = FleetRouter(_mixed_factory(model, params, gen_cfg), 2)
    ids = [fleet.submit(p) for p in PROMPTS]
    done = {}
    for _ in range(2):                      # some tokens commit first
        for c in fleet.step():
            done[c.request_id] = c
    for c in fleet.restart_replica(0):
        done[c.request_id] = c
    _drain_fleet(fleet, done)
    assert [done[i].tokens for i in ids] == ref
    summ = fleet.summary()
    assert summ["failovers"] >= 1 and summ["shed"] == 0
    assert summ["restarts"] == 1
    assert fleet.replicas[0].restarts == 1
    fleet.close()


# -- async router: overlapped worker ticks, identical tokens -----------


@pytest.mark.parametrize("tiered", [False, True],
                         ids=["plain", "tiered"])
@pytest.mark.parametrize("failover", [False, True],
                         ids=["steady", "failover"])
@pytest.mark.parametrize("make_cfg", [_greedy_cfg, _sampling_cfg],
                         ids=["greedy", "sampled"])
def test_fleet_async_parity_matrix(model_and_params,
                                   paged512_model_and_params,
                                   make_cfg, failover, tiered):
    """The async acceptance pin: an ``async_workers=True`` fleet —
    every replica ticking on its own worker thread, interleaving
    however the scheduler pleases — produces token-identical output
    to the lockstep fleet AND to a single server, greedy and sampled,
    with and without a mid-run rolling restart, with and without the
    tiered host pool underneath."""
    gen_cfg = make_cfg(max_dec=4)
    if tiered:
        model, params = paged512_model_and_params
        rng = np.random.default_rng(21)
        system = rng.integers(0, EOS, 130).tolist()
        prompts = [system + rng.integers(0, EOS, 7 + i).tolist()
                   for i in range(4)]
        kw = dict(page_size=128, pool_pages=5,
                  prefill_chunk_pages=1, prefix_sharing=True,
                  host_pool_bytes=1 << 20)
        single = GenerationServer(model, params, gen_cfg,
                                  num_slots=4,
                                  rng=jax.random.PRNGKey(7),
                                  page_size=128, pool_pages=64,
                                  prefill_chunk_pages=1,
                                  prefix_sharing=True)
    else:
        model, params = model_and_params
        prompts = PROMPTS
        kw = {}
        single = GenerationServer(model, params, gen_cfg,
                                  num_slots=6,
                                  rng=jax.random.PRNGKey(7))
    ref = [c.tokens for c in single.run(prompts)]
    single.close()
    factory = _mixed_factory(model, params, gen_cfg, **kw)

    def serve(async_workers):
        fleet = FleetRouter(factory, 2, async_workers=async_workers)
        ids = [fleet.submit(p) for p in prompts]
        done = {}
        if failover:
            for _ in range(2):
                for c in fleet.step():
                    done[c.request_id] = c
            for c in fleet.restart_replica(0):
                done[c.request_id] = c
        _drain_fleet(fleet, done)
        summ = fleet.summary()
        fleet.close()
        return [done[i].tokens for i in ids], summ

    lock_toks, _ = serve(async_workers=False)
    async_toks, summ = serve(async_workers=True)
    assert lock_toks == ref
    assert async_toks == ref
    assert summ["async_workers"] is True and summ["shed"] == 0
    if failover:
        assert summ["restarts"] == 1


def test_fleet_async_trace_span_ordering(model_and_params, tmp_path):
    """Trace reconstruction under interleaved worker ticks: the
    recorder's per-request story must stay causally ordered even
    though replica ticks come from N threads — for every request
    trace, the fleet/route span opens before any serving/request
    lifetime, every span's begin precedes its end, and the first
    serving/first_token point lands inside its request lifetime."""
    model, params = model_and_params
    gen_cfg = _greedy_cfg()
    events = tmp_path / "events.jsonl"

    def factory(name):
        return GenerationServer(model, params, gen_cfg, num_slots=2,
                                rng=jax.random.PRNGKey(7),
                                events_path=str(events))

    fleet = FleetRouter(factory, 2, events_path=str(events),
                        async_workers=True)
    ids = [fleet.submit(p) for p in PROMPTS]
    done = _drain_fleet(fleet, {})
    fleet.close()
    assert set(done) == set(ids)
    evs = read_events(str(events))
    traces = {done[i].trace_id for i in ids}
    assert len(traces) == len(ids)
    for tid in traces:
        tevs = [(n, e) for n, e in enumerate(evs)
                if e.get("trace") == tid]
        routes = [n for n, e in tevs if e["event"] == "span_begin"
                  and e["name"] == "fleet/route"]
        req_begins = [n for n, e in tevs
                      if e["event"] == "span_begin"
                      and e["name"] == "serving/request"]
        req_ends = [n for n, e in tevs if e["event"] == "span_end"
                    and e["name"] == "serving/request"]
        firsts = [n for n, e in tevs if e["event"] == "span_point"
                  and e["name"] == "serving/first_token"]
        assert len(routes) == 1
        assert len(req_begins) == len(req_ends) == 1
        assert routes[0] < req_begins[0] < req_ends[0]
        assert firsts and req_begins[0] < firsts[0] < req_ends[0]


# -- prefill/decode disaggregation -------------------------------------


@pytest.mark.parametrize("handoff", ["device", "host"])
def test_fleet_split_handoff_parity(paged512_model_and_params,
                                    handoff):
    """1 prefill + 1 decode replica: every prompt prefills on one
    server, its KV pages move across pools (device-direct and
    host-staged), and decode on the peer still produces the lockstep
    rows. Both allocators end clean — nothing leaked on either side
    of any handoff."""
    model, params = paged512_model_and_params
    gen_cfg = _greedy_cfg()
    prompts = _long_prompts()
    ref = _lockstep(model, params, prompts, gen_cfg)
    factory = _mixed_factory(model, params, gen_cfg, page_size=128,
                             pool_pages=17, prefill_chunk_pages=1)
    fleet = FleetRouter(factory, 2, prefill_replicas=1,
                        handoff=handoff)
    comps = fleet.run(prompts)
    assert [c.tokens for c in comps] == ref
    summ = fleet.summary()
    assert summ["handoffs"] == 3 and summ["shed"] == 0
    assert summ["handoff_pages"] >= summ["handoffs"] * 2  # 2pp each
    # decode landed on the decode replica, prefill never decoded
    roles = {r["role"]: r for r in summ["per_replica"]}
    assert roles["decode"]["decode_tokens"] > 0
    assert roles["prefill"]["decode_tokens"] == 0
    for rep in fleet.replicas:
        rep.server._alloc.check()
        assert rep.server._alloc.pages_in_use == 0
    fleet.close()


def test_fleet_async_d2d_handoff_smoke(paged512_model_and_params,
                                       tmp_path, monkeypatch):
    """CI smoke (`-k smoke`), async d2d edition: a 1 prefill + 1
    decode ASYNC fleet moves every KV handoff device-to-device with
    ZERO host copies — `jax.device_get` never runs for a handoff (the
    handoff-writer thread stays idle and is counted), the d2d/host
    counters split 3/0, the handoff histogram fills, no
    `serving_spill`-style host staging appears on the trace, and the
    tokens still equal the lockstep rows. events.jsonl lands under
    tmp_path for CI's failure-diagnostics artifact."""
    model, params = paged512_model_and_params
    gen_cfg = _greedy_cfg()
    prompts = _long_prompts()
    ref = _lockstep(model, params, prompts, gen_cfg)
    events = tmp_path / "events.jsonl"
    host_copies = []
    real = jax.device_get

    def counting_get(x):
        import threading as _t
        name = _t.current_thread().name
        if name.startswith("fleet-"):
            host_copies.append(name)
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    def factory(name):
        return GenerationServer(model, params, gen_cfg, num_slots=2,
                                rng=jax.random.PRNGKey(7),
                                page_size=128, pool_pages=17,
                                prefill_chunk_pages=1,
                                events_path=str(events))

    fleet = FleetRouter(factory, 2, prefill_replicas=1,
                        handoff="device", async_workers=True,
                        events_path=str(events))
    comps = fleet.run(prompts)
    summ = fleet.summary()
    fleet.close()
    assert [c.tokens for c in comps] == ref
    assert summ["handoffs"] == 3
    assert summ["handoff_d2d"] == 3      # every handoff stayed d2d
    assert summ["handoff_host"] == 0
    assert summ["handoff_p99_ms"] >= summ["handoff_p50_ms"] > 0
    assert not host_copies               # zero host copies, any thread
    for rep in fleet.replicas:
        rep.server.check_alloc()        # the surface-locked spelling
        assert rep.server._alloc.pages_in_use == 0
    evs = read_events(str(events))
    kinds = {e["event"] for e in evs}
    assert "fleet_handoff" in kinds
    # no host staging anywhere near the handoff trace: neither the
    # fleet's staging stage nor a serving-side spill ever fired
    assert "fleet_handoff_staged" not in kinds
    assert "serving_spill" not in kinds
    for e in evs:
        if e["event"] == "fleet_handoff":
            assert e["mode"] == "device"


def test_fleet_async_overlap_ratio_beats_lockstep(model_and_params,
                                                  tmp_path):
    """The overlap A/B pin (docs/observability.md, "Thread
    timeline"): serving the SAME trace, the lockstep router scores
    exactly 1/N on ``overlap_ratio`` (one lane mid-tick at a time by
    construction) and the async router must score STRICTLY more —
    worker threads whose tick intervals never overlap would mean the
    async fleet is lockstep with extra steps. Also pins the
    ``summary()`` plumbing the fleet bench records ride
    (``overlap_ratio`` + per-thread ``thread_util``) and dumps the
    async run's merged Perfetto timeline as timeline_fleet_async.json
    for CI's failure-diagnostics artifact."""
    model, params = model_and_params
    gen_cfg = _greedy_cfg()
    factory = _mixed_factory(model, params, gen_cfg)
    was = timeline.enabled()
    timeline.set_enabled(True)
    try:
        def serve(async_workers):
            fleet = FleetRouter(factory, 2,
                                async_workers=async_workers)
            ids = [fleet.submit(p) for p in PROMPTS]
            done = _drain_fleet(fleet, {})
            assert set(done) == set(ids)
            summ = fleet.summary()
            snap = timeline.get_timeline().snapshot(since=fleet._t0)
            fleet.close()
            return summ, snap

        lock_summ, _ = serve(async_workers=False)
        async_summ, async_snap = serve(async_workers=True)
    finally:
        timeline.set_enabled(was)

    # lockstep floor: depth never exceeds 1 => exactly 1/N
    assert lock_summ["overlap_ratio"] == pytest.approx(1 / 2)
    # the tentpole claim, falsifiable: async genuinely overlaps
    assert async_summ["overlap_ratio"] > lock_summ["overlap_ratio"]
    assert async_summ["overlap_ratio"] <= 1.0
    # per-thread utilization rides the same summary
    util = async_summ["thread_util"]
    assert {"fleet-worker-0", "fleet-worker-1"} <= set(util)
    assert all(0.0 <= u <= 1.0 for u in util.values())

    # one Perfetto thread row per instrumented thread, artifact-ready
    trace = export.chrome_trace([], timeline=async_snap)
    rows = {e["args"]["name"] for e in trace["traceEvents"]
            if e.get("name") == "thread_name"}
    assert {"fleet-router", "fleet-worker-0",
            "fleet-worker-1"} <= rows
    out = tmp_path / "timeline_fleet_async.json"
    out.write_text(json.dumps(trace))
    assert json.loads(out.read_text())["displayTimeUnit"] == "ms"


def test_fleet_async_handoff_reconstructs_from_timeline(
        paged512_model_and_params, tmp_path):
    """Handoff reconstruction from the thread timeline ALONE — the
    event stream only mints the trace ids: each host-staged handoff
    shows up as a trace-tagged ``handoff_host`` interval on the
    writer track, preceded by prefill-lane tick work and followed by
    decode-lane tick work, with the router's harvest waits accounted
    on its own track."""
    model, params = paged512_model_and_params
    gen_cfg = _greedy_cfg()
    prompts = _long_prompts()
    events = tmp_path / "events.jsonl"
    factory = _mixed_factory(model, params, gen_cfg, page_size=128,
                             pool_pages=17, prefill_chunk_pages=1)
    was = timeline.enabled()
    timeline.set_enabled(True)
    try:
        fleet = FleetRouter(factory, 2, prefill_replicas=1,
                            handoff="host", async_workers=True,
                            events_path=str(events))
        comps = fleet.run(prompts)
        summ = fleet.summary()
        snap = timeline.get_timeline().snapshot(since=fleet._t0)
        fleet.close()
    finally:
        timeline.set_enabled(was)

    assert summ["handoffs"] == 3 and summ["handoff_host"] == 3
    traces = {c.trace_id for c in comps}
    handoffs = [iv for iv in snap["fleet-handoff-writer"]
                if iv[0] == "handoff_host"]
    # one staged interval per handoff, each tagged with the trace id
    # of a real completion — and all three requests distinct
    assert len(handoffs) == 3
    assert {iv[3] for iv in handoffs} <= traces
    assert len({iv[3] for iv in handoffs}) == 3
    roles = [r["role"] for r in summ["per_replica"]]
    pticks = [iv for iv in snap[f"fleet-worker-{roles.index('prefill')}"]
              if iv[0] == "tick"]
    dticks = [iv for iv in snap[f"fleet-worker-{roles.index('decode')}"]
              if iv[0] == "tick"]
    for _, h0, h1, tr in handoffs:
        assert h1 >= h0 and tr is not None
        # the prefill lane was ticking before the staging began, and
        # the decode lane ticked on past its completion — the
        # prefill -> stage -> decode story reads off the intervals
        assert any(t0 < h0 for _, t0, _, _ in pticks)
        assert any(t1 > h1 for _, _, t1, _ in dticks)
    # the writer's idle waits and the router's harvest waits are
    # attributed, not invisible
    assert any(iv[0] == "idle" for iv in snap["fleet-handoff-writer"])
    assert any(iv[0] == "harvest_wait"
               for iv in snap["fleet-router"])


def test_fleet_split_handoff_int8_scales(paged512_model_and_params):
    """The handoff tree carries the int8 pools' scale leaves: a
    disaggregated fleet over kv_cache_dtype="int8" replicas stays
    token-exact with the bf16 lockstep reference (per-token abs-max
    quantization is argmax-invisible, and a round-trip through
    gather -> host staging -> scatter must keep it so)."""
    model, params = paged512_model_and_params
    icfg = GPTConfig(**{**PCFG512.__dict__, "kv_cache_dtype": "int8"})
    imodel = GPTForPretraining(icfg)
    gen_cfg = _greedy_cfg()
    prompts = _long_prompts(seed=4)
    ref = _lockstep(model, params, prompts, gen_cfg)
    factory = _mixed_factory(imodel, params, gen_cfg, page_size=128,
                             pool_pages=17, prefill_chunk_pages=1)
    fleet = FleetRouter(factory, 2, prefill_replicas=1,
                        handoff="host")
    comps = fleet.run(prompts)
    assert [c.tokens for c in comps] == ref
    assert fleet.summary()["handoffs"] == 3
    for rep in fleet.replicas:
        rep.server._alloc.check()
        assert rep.server._alloc.pages_in_use == 0
    fleet.close()


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_kv_page_gather_scatter_roundtrip_across_pools(kv_dtype):
    """The handoff's device ops, pinned at the array level: pages
    gathered from one pool land byte-identical in ANOTHER pool under
    remapped page ids (including a host-staging hop), int8 pools move
    their fp32 scale pages in the same tree, and non-pool leaves plus
    untouched destination pages are left alone."""
    from paddlefleetx_tpu.models.gpt.generation import (
        gather_kv_pages, scatter_kv_pages,
    )
    rng = np.random.default_rng(0)
    names = ["cached_key", "cached_value"]
    if kv_dtype == "int8":
        names += ["cached_key_scale", "cached_value_scale"]

    def pool(n_pages, fill):
        layer = {}
        for name in names:
            if name.endswith("_scale"):
                shape, dt = (n_pages, 2, 1, 128), np.float32
            else:
                shape, dt = (n_pages, 2, 128, 4), (
                    np.int8 if kv_dtype == "int8" else np.float32)
            arr = rng.normal(0, 20, shape) if fill else np.zeros(shape)
            layer[name] = jnp.asarray(arr.astype(dt))
        layer["cache_index"] = jnp.asarray([7], jnp.int32)
        return {"layer_0": layer}

    src, dst = pool(6, fill=True), pool(8, fill=False)
    src_pids, dst_pids = [2, 5], [7, 1]         # the remap
    data = gather_kv_pages(src, jnp.asarray(src_pids, jnp.int32))
    staged = jax.device_get(data)               # host-staging hop
    out = scatter_kv_pages(dst, staged,
                           jnp.asarray(dst_pids, jnp.int32))
    for name in names:
        got = np.asarray(out["layer_0"][name])
        want = np.asarray(src["layer_0"][name])
        for d, s in zip(dst_pids, src_pids):
            np.testing.assert_array_equal(got[d], want[s])
        untouched = [p for p in range(8) if p not in dst_pids]
        assert not np.asarray(got[untouched]).any()
    np.testing.assert_array_equal(
        np.asarray(out["layer_0"]["cache_index"]), [7])


# -- routing: affinity, spillover, shed --------------------------------


def test_fleet_affinity_routes_to_prefix_holder(
        paged512_model_and_params):
    """A request sharing a live system prefix routes to the replica
    already holding those pages, even when the peer is emptier —
    registry affinity beats least-depth."""
    model, params = paged512_model_and_params
    gen_cfg = _greedy_cfg()
    rng = np.random.default_rng(11)
    system = rng.integers(0, EOS, 130).tolist()     # 1 full page
    p1 = system + rng.integers(0, EOS, 40).tolist()
    p2 = system + rng.integers(0, EOS, 10).tolist()
    ref = _lockstep(model, params, [p1, p2], gen_cfg)
    factory = _mixed_factory(model, params, gen_cfg, page_size=128,
                             pool_pages=17, prefill_chunk_pages=1)
    fleet = FleetRouter(factory, 2)
    g1 = fleet.submit(p1)
    home = fleet._reqs[g1]["replica"]
    done = {}
    sys_key = page_prefix_keys(p1, 128)[0]
    for _ in range(6):          # prefill publishes the system page
        for c in fleet.step():
            done[c.request_id] = c
        alloc = fleet.replicas[home].server._alloc
        if alloc.lookup_prefix(sys_key) is not None:
            break
    assert fleet.replicas[home].server._alloc.lookup_prefix(
        sys_key) is not None
    g2 = fleet.submit(p2)
    assert fleet._reqs[g2]["replica"] == home   # affinity won
    _drain_fleet(fleet, done)
    assert [done[g1].tokens, done[g2].tokens] == ref
    summ = fleet.summary()
    assert summ["routed_affinity"] >= 1
    assert summ["shed"] == 0
    fleet.close()


def test_fleet_spillover_preserves_sampled_parity(model_and_params):
    """An admission refusal spills to the next-ranked replica and the
    nonce is consumed only on the successful admit — the sampled
    stream is unchanged by where (or on which attempt) a request
    lands."""
    from paddlefleetx_tpu.core.resilience import FaultInjector
    model, params = model_and_params
    gen_cfg = _sampling_cfg()
    single = GenerationServer(model, params, gen_cfg, num_slots=6,
                              rng=jax.random.PRNGKey(7))
    ref = [c.tokens for c in single.run(PROMPTS)]

    def factory(name):
        # replica0's first submit fails -> the router must spill that
        # request over to replica1 without burning its nonce
        faults = FaultInjector("admit_fail@req=1", kill_mode="raise") \
            if name == "replica0" else None
        return GenerationServer(model, params, gen_cfg, num_slots=2,
                                rng=jax.random.PRNGKey(7),
                                fault_injector=faults)

    fleet = FleetRouter(factory, 2)
    comps = fleet.run(PROMPTS)
    assert [c.tokens for c in comps] == ref
    summ = fleet.summary()
    assert summ["spillover"] >= 1 and summ["shed"] == 0
    fleet.close()


def test_fleet_sheds_only_when_all_refuse(model_and_params):
    """RequestShed surfaces only after EVERY replica refused; a shed
    must not burn a sampling nonce (the next admitted request draws
    exactly what it would have without the shed)."""
    from paddlefleetx_tpu.core.resilience import FaultInjector
    model, params = model_and_params
    gen_cfg = _sampling_cfg()
    single = GenerationServer(model, params, gen_cfg, num_slots=6,
                              rng=jax.random.PRNGKey(7))
    ref = [c.tokens for c in single.run(PROMPTS[1:])]

    def factory(name):
        return GenerationServer(
            model, params, gen_cfg, num_slots=2,
            rng=jax.random.PRNGKey(7),
            fault_injector=FaultInjector("admit_fail@req=1",
                                         kill_mode="raise"))

    fleet = FleetRouter(factory, 1)
    with pytest.raises(RequestShed, match="every eligible replica"):
        fleet.submit(PROMPTS[0])
    comps = fleet.run(PROMPTS[1:])
    assert [c.tokens for c in comps] == ref     # nonce 0 not burned
    summ = fleet.summary()
    assert summ["shed"] == 1 and summ["submitted"] == 6
    fleet.close()


# -- observability: one trace per request, live fleet endpoint ---------


def test_fleet_failover_trace_continuity(model_and_params, tmp_path):
    """events.jsonl alone reconstructs a failover: each failed-over
    request reads as ONE trace id with a fleet/route root, TWO
    serving/request lifetimes (original + resumed) and a
    fleet/failover span between them."""
    model, params = model_and_params
    gen_cfg = _greedy_cfg()
    events = tmp_path / "events.jsonl"

    def factory(name):
        return GenerationServer(model, params, gen_cfg, num_slots=2,
                                rng=jax.random.PRNGKey(7),
                                events_path=str(events))

    fleet = FleetRouter(factory, 2, events_path=str(events))
    ids = [fleet.submit(p) for p in PROMPTS]
    done = {}
    for _ in range(2):
        for c in fleet.step():
            done[c.request_id] = c
    for c in fleet.restart_replica(0):
        done[c.request_id] = c
    _drain_fleet(fleet, done)
    assert fleet.summary()["failovers"] >= 1
    fleet.close()

    # every request: one distinct trace, rooted in fleet/route
    assert len({done[i].trace_id for i in ids}) == len(ids)
    evs = read_events(str(events))
    failed_over = [e for e in evs if e["event"] == "fleet_failover"]
    assert failed_over
    for ev in failed_over:
        tid = ev["trace"]
        routes = [e for e in evs if e["event"] == "span_begin"
                  and e["name"] == "fleet/route"
                  and e["trace"] == tid]
        lives = [e for e in evs if e["event"] == "span_begin"
                 and e["name"] == "serving/request"
                 and e["trace"] == tid]
        fails = [e for e in evs if e["event"] == "span_begin"
                 and e["name"] == "fleet/failover"
                 and e["trace"] == tid]
        assert len(routes) == 1
        assert len(lives) == 2      # original + resumed lifetime
        assert len(fails) == 1
        assert lives[0]["span"] != lives[1]["span"]


#: one Prometheus 0.0.4 sample line (# TYPE comments aside)
_PROM_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*(\{le="[^"]+"\})? [-+0-9.einfE]+$')


def test_fleet_metrics_endpoint_smoke(paged512_model_and_params,
                                      tmp_path, monkeypatch):
    """CI smoke (`-k smoke`), fleet edition: two paged interpret-mode
    replicas behind the router with PFX_METRICS_PORT=0, a shared
    system prefix in the trace, one drain->failover rolling restart
    mid-run; /metrics scrapes as Prometheus text with the fleet
    gauges/histogram present and /healthz aggregates per-replica
    state (ok while ANY replica serves). Scraped bodies land as
    metrics_scrape_fleet_* files for CI's failure-diagnostics
    artifact."""
    model, params = paged512_model_and_params
    monkeypatch.setenv("PFX_METRICS_PORT", "0")
    obs_server.stop()              # a fresh singleton for this test
    events = tmp_path / "events.jsonl"
    gen_cfg = _greedy_cfg(max_dec=6)
    rng = np.random.default_rng(9)
    system = rng.integers(0, EOS, 130).tolist()
    prompts = [system + rng.integers(0, EOS, n).tolist()
               for n in (40, 20, 10, 30)]
    ref = _lockstep(model, params, prompts, gen_cfg)

    def get(url_path):
        try:
            with urllib.request.urlopen(msrv.url(url_path),
                                        timeout=10) as resp:
                return resp.status, resp.read().decode("utf-8")
        except urllib.error.HTTPError as err:
            return err.code, err.read().decode("utf-8")

    metrics.set_enabled(True)
    reg = metrics.get_registry()
    reg.reset()
    try:
        factory = _mixed_factory(model, params, gen_cfg,
                                 page_size=128, pool_pages=17,
                                 prefill_chunk_pages=1,
                                 events_path=str(events))
        fleet = FleetRouter(factory, 2, events_path=str(events))
        msrv = obs_server.get_server()
        assert msrv is not None and msrv.port > 0
        ids = [fleet.submit(p) for p in prompts[:2]]
        done = {}
        # step until some replica published the shared system page,
        # then submit the followers — they route by prefix affinity
        sys_key = page_prefix_keys(prompts[0], 128)[0]
        for _ in range(6):            # prefill + first decode ticks
            for c in fleet.step():
                done[c.request_id] = c
            if any(r.server._alloc.lookup_prefix(sys_key) is not None
                   for r in fleet.replicas):
                break
        ids += [fleet.submit(p) for p in prompts[2:]]

        # mid-run: exposition parses, fleet gauges are live
        code, mbody = get("/metrics")
        assert code == 200
        for line in mbody.splitlines():
            assert line.startswith("# TYPE ") or \
                _PROM_SAMPLE_RE.match(line), \
                f"bad exposition line: {line!r}"
        assert "pfx_fleet_replicas_ok 2" in mbody
        assert "pfx_fleet_submitted" in mbody
        code, hbody = get("/healthz")
        assert code == 200
        health = json.loads(hbody)
        assert health["status"] == "ok"
        assert health["replicas_ok"] == 2
        assert [r["name"] for r in health["replicas"]] == \
            ["replica0", "replica1"]
        (tmp_path / "metrics_scrape_fleet_metrics.txt"
         ).write_text(mbody)
        (tmp_path / "metrics_scrape_fleet_healthz.json"
         ).write_text(hbody)

        # one rolling restart mid-run: drain -> failover -> fresh
        # server, and the fleet endpoint survives the swap
        for c in fleet.restart_replica(0):
            done[c.request_id] = c
        code, hbody = get("/healthz")
        assert code == 200            # the peer kept serving
        assert json.loads(hbody)["replicas"][0]["restarts"] == 1
        _drain_fleet(fleet, done)
        assert [done[i].tokens for i in ids] == ref

        # finished fleet: TTFT histogram exported, healthz flips 503
        # only once EVERY replica drains
        code, mbody = get("/metrics")
        assert code == 200
        assert "pfx_fleet_ttft_ms_bucket" in mbody
        assert 'le="+Inf"' in mbody
        summ = fleet.summary()
        assert summ["failovers"] >= 1 and summ["shed"] == 0
        assert summ["routed_affinity"] >= 1     # shared system prefix
        assert summ["ttft_p99_ms"] >= summ["ttft_p50_ms"] > 0
        fleet.replicas[0].server.drain()
        code, _ = get("/healthz")
        assert code == 200
        fleet.replicas[1].server.drain()
        code, hbody = get("/healthz")
        assert code == 503
        assert json.loads(hbody)["status"] == "draining"
        (tmp_path / "metrics_scrape_fleet_healthz_draining.json"
         ).write_text(hbody)
        evs = read_events(str(events))
        kinds = {e["event"] for e in evs}
        assert {"fleet_start", "fleet_route", "fleet_restart_begin",
                "fleet_restart_end", "fleet_failover",
                "serving_start"} <= kinds
        fleet.close()
    finally:
        obs_server.stop()
        metrics.set_enabled(False)
        reg.reset()
    assert obs_server.get_server() is None


# -- construction contracts --------------------------------------------


def test_fleet_constructor_validation(model_and_params):
    model, params = model_and_params
    factory = _mixed_factory(model, params, _greedy_cfg())
    with pytest.raises(ValueError, match="num_replicas"):
        FleetRouter(factory, 0)
    with pytest.raises(ValueError, match="prefill_replicas"):
        FleetRouter(factory, 2, prefill_replicas=2)
    with pytest.raises(ValueError, match="handoff"):
        FleetRouter(factory, 2, handoff="rdma")
    fleet = FleetRouter(factory, 2, prefill_replicas=1)
    assert [r.role for r in fleet.replicas] == ["prefill", "decode"]
    assert isinstance(fleet.replicas[0], FleetReplica)
    fleet.close()


# -- tiered replicas: warm rolling restarts ----------------------------


def test_rolling_restart_warm_prefix_store(paged512_model_and_params,
                                           tmp_path):
    """A rolling restart of tiered replicas hands each one's hot
    prefix store to its replacement through the checkpoint-manifest
    round trip (docs/fleet_serving.md, "Warm starts"): the second
    wave of conversations — resubmitted after EVERY replica was
    swapped — is served token-identically to an untiered unlimited
    fleet, with the restarted replicas rehydrating instead of
    re-prefilling."""
    model, params = paged512_model_and_params
    gen_cfg = _greedy_cfg(max_dec=6)
    rng = np.random.default_rng(11)
    system = rng.integers(0, EOS, 130).tolist()
    prompts = [system + rng.integers(0, EOS, 7 + i).tolist()
               for i in range(3)]

    def run_fleet(factory, store_dir):
        fleet = FleetRouter(factory, 2, prefix_store_dir=store_dir)
        done = {}
        for p in prompts:
            done[fleet.submit(p)] = None
        _drain_fleet(fleet, done)
        fleet.rolling_restart()
        for p in prompts:
            done[fleet.submit(p)] = None
        _drain_fleet(fleet, done)
        reps = [(r.restarts, r.server.summary())
                for r in fleet.replicas]
        toks = [done[i].tokens for i in sorted(done)]
        fleet.close()
        return toks, reps

    tiered_kw = dict(page_size=128, pool_pages=5,
                     prefill_chunk_pages=1, prefix_sharing=True,
                     host_pool_bytes=1 << 20)
    t_toks, t_reps = run_fleet(
        _mixed_factory(model, params, gen_cfg, **tiered_kw),
        str(tmp_path))
    u_toks, _ = run_fleet(
        _mixed_factory(model, params, gen_cfg, page_size=128,
                       pool_pages=64, prefill_chunk_pages=1,
                       prefix_sharing=True), None)
    assert t_toks == u_toks
    # every replica was swapped, the store round-tripped through disk
    # (committed-last manifest), and the fresh servers served wave 2
    # from rehydration
    assert all(restarts == 1 for restarts, _ in t_reps)
    assert all((tmp_path / f"replica{i}_prefix_store" /
                "pfx_manifest.json").exists() for i in range(2))
    assert sum(s["rehydrates"] for _, s in t_reps) > 0
    assert all(s["prefill_chunks"] == 0 for _, s in t_reps
               if s["rehydrates"] > 0)
