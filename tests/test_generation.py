import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import GPTConfig, GPTForPretraining
from paddlefleetx_tpu.models.gpt.generation import (
    GenerationConfig, generate, left_pad_batch,
)
from paddlefleetx_tpu.models.gpt.processors import (
    min_length_processor, repetition_penalty_processor, top_k_filter,
    top_p_filter,
)

CFG = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_position_embeddings=48,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
EOS, PAD = 95, 95


@pytest.fixture(scope="module")
def model_and_params():
    model = GPTForPretraining(CFG)
    variables = model.init({"params": jax.random.key(0)},
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables["params"]


def test_unrolled_twin_param_layout(model_and_params):
    """The decode path unrolls the layer scan: the twin's params must
    expand the stacked ``decoder`` subtree into per-layer copies whose
    leaves are the stack's slices, leaving everything else intact."""
    from paddlefleetx_tpu.models.gpt.generation import _unrolled_twin
    model, params = model_and_params
    twin, tp = _unrolled_twin(model, params)
    assert twin.config.scan_layers is False
    gpt = tp["gpt"]
    assert "decoder" not in gpt
    assert {f"decoder_{i}" for i in range(CFG.num_layers)} <= set(gpt)
    stacked = params["gpt"]["decoder"]
    for i in range(CFG.num_layers):
        jax.tree.map(
            lambda full, sliced: np.testing.assert_array_equal(
                np.asarray(full[i]), np.asarray(sliced)),
            dict(stacked), gpt[f"decoder_{i}"])
    # twin logits == scanned logits (prefill path, both models)
    ids = jnp.arange(8, dtype=jnp.int32)[None, :]
    a = model.apply({"params": params}, ids)
    b = twin.apply({"params": tp}, ids)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-6)


def test_greedy_matches_argmax_unrolled(model_and_params):
    """Cached greedy decode == repeatedly re-running the full forward."""
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 90, (2, 7)), jnp.int32)
    gen_cfg = GenerationConfig(max_dec_len=6, decode_strategy="greedy_search",
                               eos_token_id=EOS, pad_token_id=PAD)
    got = np.asarray(generate(model, params, prompt, None,
                              jax.random.key(1), gen_cfg))

    seq = prompt
    expect = []
    for _ in range(6):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)
        expect.append(np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(expect, 1))


def test_num_return_sequences_tiles_prompts(model_and_params):
    """num_return_sequences=N: N rows per prompt (prompt-major, the
    reference's expand_inputs_for_generation), each an independent
    sample; under greedy decoding all copies are identical."""
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 90, (2, 7)), jnp.int32)
    greedy = GenerationConfig(max_dec_len=5,
                              decode_strategy="greedy_search",
                              num_return_sequences=3,
                              eos_token_id=EOS, pad_token_id=PAD)
    out = np.asarray(generate(model, params, prompt, None,
                              jax.random.key(1), greedy))
    assert out.shape == (6, 5)
    base = GenerationConfig(max_dec_len=5,
                            decode_strategy="greedy_search",
                            eos_token_id=EOS, pad_token_id=PAD)
    single = np.asarray(generate(model, params, prompt, None,
                                 jax.random.key(1), base))
    for i in range(2):
        for j in range(3):
            np.testing.assert_array_equal(out[i * 3 + j], single[i])

    sampling = GenerationConfig(max_dec_len=8,
                                decode_strategy="sampling", top_k=50,
                                num_return_sequences=4,
                                eos_token_id=EOS, pad_token_id=PAD)
    s = np.asarray(generate(model, params, prompt, None,
                            jax.random.key(3), sampling))
    assert s.shape == (8, 8)
    # the copies explore different continuations
    assert any(not np.array_equal(s[0], s[j]) for j in range(1, 4))


def test_beam_search_k1_equals_greedy(model_and_params):
    """Beam width 1 degenerates to greedy decoding exactly.

    Only while no EOS candidate enters the finished pool: beam search
    ranks COMPLETE hypotheses, so with length_penalty=0 a shorter
    sequence that ends in a near-argmax EOS can outrank the live beam
    — correct beam semantics, not a greedy mismatch. min_dec_len bans
    EOS (identically on both paths) to pin the step-wise equivalence
    itself rather than this untrained model's EOS coin-flips."""
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, 90, (2, 7)), jnp.int32)
    greedy = GenerationConfig(max_dec_len=6, min_dec_len=6,
                              decode_strategy="greedy_search",
                              eos_token_id=EOS, pad_token_id=PAD)
    beam1 = GenerationConfig(max_dec_len=6, min_dec_len=6,
                             decode_strategy="beam_search",
                             num_beams=1, eos_token_id=EOS,
                             pad_token_id=PAD)
    g = np.asarray(generate(model, params, prompt, None,
                            jax.random.key(0), greedy))
    bm = np.asarray(generate(model, params, prompt, None,
                             jax.random.key(0), beam1))
    np.testing.assert_array_equal(g, bm)


def test_beam_search_beats_or_matches_greedy_likelihood(model_and_params):
    """The best beam's model log-probability is >= the greedy
    sequence's (the point of beam search), and the returned beams are
    score-ordered."""
    model, params = model_and_params
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(0, 90, (3, 6)), jnp.int32)
    dec = 6

    def seq_logprob(tokens):
        # tokens [b, dec]; teacher-force through the model
        full = jnp.concatenate([prompt, jnp.asarray(tokens)], axis=1)
        logits = model.apply({"params": params}, full)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        total = np.zeros(full.shape[0])
        for t in range(dec):
            pos = prompt.shape[1] - 1 + t
            total += np.asarray(lp[np.arange(full.shape[0]), pos,
                                   tokens[:, t]])
        return total

    greedy = GenerationConfig(max_dec_len=dec,
                              decode_strategy="greedy_search",
                              eos_token_id=EOS, pad_token_id=PAD)
    beam = GenerationConfig(max_dec_len=dec,
                            decode_strategy="beam_search", num_beams=4,
                            eos_token_id=EOS, pad_token_id=PAD)
    g = np.asarray(generate(model, params, prompt, None,
                            jax.random.key(0), greedy))
    bm = np.asarray(generate(model, params, prompt, None,
                             jax.random.key(0), beam))
    assert bm.shape == (3, dec)        # num_return_sequences=1 default
    # neither output hit EOS in these tiny random models; compare raw
    # teacher-forced likelihoods
    if not (g == EOS).any() and not (bm == EOS).any():
        lg, lb = seq_logprob(g), seq_logprob(bm)
        assert (lb >= lg - 1e-4).all(), (lb, lg)


def test_beam_search_returns_n_best_ordered(model_and_params):
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(6).integers(0, 90, (2, 5)), jnp.int32)
    beam = GenerationConfig(max_dec_len=4,
                            decode_strategy="beam_search", num_beams=4,
                            num_return_sequences=3,
                            eos_token_id=EOS, pad_token_id=PAD)
    out = np.asarray(generate(model, params, prompt, None,
                              jax.random.key(0), beam))
    assert out.shape == (6, 4)         # 2 prompts x 3 beams
    # distinct beams per prompt (width-4 search over a 100-vocab model)
    assert not np.array_equal(out[0], out[1])


def test_beam_config_validation():
    import pytest
    with pytest.raises(ValueError):
        GenerationConfig(decode_strategy="beam_search", num_beams=2,
                         num_return_sequences=3)
    with pytest.raises(ValueError):
        GenerationConfig(decode_strategy="nope")
    with pytest.raises(ValueError):  # groups must divide beams
        GenerationConfig(decode_strategy="beam_search", num_beams=4,
                         num_beam_groups=3, diversity_rate=1.0)
    with pytest.raises(ValueError):  # grouped search needs a penalty
        GenerationConfig(decode_strategy="beam_search", num_beams=4,
                         num_beam_groups=2, diversity_rate=0.0)


def test_beam_search_repetition_penalty_k1_equals_greedy(
        model_and_params):
    """Beam scores accumulate PROCESSED log-probs (reference/HF
    semantics): with repetition_penalty != 1.0 a width-1 beam must
    still reproduce greedy decoding under the same penalty — both
    argmax the same processed distribution each step."""
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(11).integers(0, 90, (2, 7)), jnp.int32)
    kw = dict(max_dec_len=6, repetition_penalty=1.5,
              eos_token_id=EOS, pad_token_id=PAD)
    g = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(decode_strategy="greedy_search", **kw)))
    bm = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(decode_strategy="beam_search", num_beams=1,
                         **kw)))
    np.testing.assert_array_equal(g, bm)


def test_group_beam_search_diversifies_first_token(model_and_params):
    """Diverse (group) beam search: with a strong Hamming penalty the
    two groups must pick DIFFERENT first tokens, while vanilla beam
    search's two best hypotheses share the greedy first token when its
    continuation dominates; group 0 must be unaffected by grouping
    (it pays no penalty) and equal the greedy sequence."""
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(12).integers(0, 90, (3, 6)), jnp.int32)
    dec = 5
    kw = dict(max_dec_len=dec, eos_token_id=EOS, pad_token_id=PAD)
    greedy = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(decode_strategy="greedy_search", **kw)))
    grouped = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(decode_strategy="beam_search", num_beams=2,
                         num_beam_groups=2, diversity_rate=100.0,
                         num_return_sequences=2, **kw)))
    assert grouped.shape == (6, dec)
    for p in range(3):
        a, b = grouped[2 * p], grouped[2 * p + 1]
        assert a[0] != b[0], (p, a, b)
        # the unpenalized group's best hypothesis == greedy
        assert (a == greedy[p]).all() or (b == greedy[p]).all(), \
            (p, a, b, greedy[p])


def test_group_beam_search_negligible_rate_groups_agree(
        model_and_params):
    """With kg=1 per group and a negligible diversity rate every group
    runs an independent width-1 (greedy) search from the same prompt —
    all returned rows must agree (and equal greedy). Pins that the
    group plumbing itself doesn't perturb scores."""
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(13).integers(0, 90, (2, 7)), jnp.int32)
    dec = 5
    kw = dict(max_dec_len=dec, eos_token_id=EOS, pad_token_id=PAD)
    greedy = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(decode_strategy="greedy_search", **kw)))
    grouped = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(decode_strategy="beam_search", num_beams=2,
                         num_beam_groups=2, diversity_rate=1e-9,
                         num_return_sequences=2, **kw)))
    for p in range(2):
        np.testing.assert_array_equal(grouped[2 * p], greedy[p])
        np.testing.assert_array_equal(grouped[2 * p + 1], greedy[p])


def test_left_padded_prompt_matches_unpadded(model_and_params):
    """Generation from a left-padded prompt == the unpadded prompt."""
    model, params = model_and_params
    rng = np.random.default_rng(1)
    short = rng.integers(0, 90, 5).tolist()
    ids, mask = left_pad_batch([short, rng.integers(0, 90, 9).tolist()],
                               PAD)
    gen_cfg = GenerationConfig(max_dec_len=5,
                               decode_strategy="greedy_search",
                               eos_token_id=EOS, pad_token_id=PAD)
    padded_out = np.asarray(generate(model, params, jnp.asarray(ids),
                                     jnp.asarray(mask), jax.random.key(0),
                                     gen_cfg))
    solo = jnp.asarray([short], jnp.int32)
    solo_out = np.asarray(generate(model, params, solo, None,
                                   jax.random.key(0), gen_cfg))
    np.testing.assert_array_equal(padded_out[0], solo_out[0])


def test_eos_finishes_row(model_and_params):
    model, params = model_and_params
    prompt = jnp.zeros((1, 4), jnp.int32)
    # force EOS immediately via min_dec_len=0 and a doctored prompt is
    # fragile; instead decode long enough that EOS eventually samples
    gen_cfg = GenerationConfig(max_dec_len=20, temperature=10.0,
                               eos_token_id=EOS, pad_token_id=94)
    out = np.asarray(generate(model, params, prompt, None,
                              jax.random.key(3), gen_cfg))[0]
    if EOS in out.tolist():
        after = out.tolist()[out.tolist().index(EOS) + 1:]
        assert all(t == 94 for t in after)


def test_capacity_guard(model_and_params):
    model, params = model_and_params
    prompt = jnp.zeros((1, 40), jnp.int32)
    gen_cfg = GenerationConfig(max_dec_len=20, eos_token_id=EOS,
                               pad_token_id=PAD)
    with pytest.raises(ValueError, match="cache capacity"):
        generate(model, params, prompt, None, jax.random.key(0), gen_cfg)


def test_top_k_filter():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    out = np.asarray(top_k_filter(logits, 2))
    assert out[0, 1] == 5.0 and out[0, 2] == 3.0
    assert out[0, 0] < -1e8 and out[0, 3] < -1e8


def test_top_p_filter_keeps_nucleus():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    out = np.asarray(top_p_filter(logits, 0.7))
    # 0.5 < 0.7 so second token kept too; third pushes past 0.8
    assert np.isfinite(out[0, 0]) and np.isfinite(out[0, 1])
    assert out[0, 2] < -1e8 and out[0, 3] < -1e8


def test_top_p_fast_path_matches_full_sort():
    """The already_top_k fast path (lax.top_k + full-mass denominator)
    must produce the identical kept set as the full-sort path after
    top_k filtering — including exact ties at the k-th value, where a
    naive k-value softmax would shift the nucleus boundary."""
    rng = np.random.default_rng(11)
    cases = [
        jnp.asarray(rng.normal(size=(4, 997)), jnp.float32),
        # exact ties straddling the k-th position
        jnp.asarray([[1.0] + [0.0] * 5 + [-2.0] * 10], jnp.float32),
        jnp.asarray([[3.0, 3.0, 3.0, 1.0, 1.0, 1.0, 0.0]], jnp.float32),
    ]
    from paddlefleetx_tpu.models.gpt.processors import (
        top_k_top_p_filter,
    )
    for logits in cases:
        for k in (2, 5):
            for p in (0.3, 0.5, 0.75, 0.95):
                filtered = top_k_filter(logits, k)
                slow = np.asarray(top_p_filter(filtered, p))
                fast = np.asarray(top_p_filter(filtered, p,
                                               already_top_k=k))
                fused = np.asarray(top_k_top_p_filter(logits, k, p))
                kept = np.isfinite(slow) & (slow > -1e8)
                np.testing.assert_array_equal(
                    kept, np.isfinite(fast) & (fast > -1e8),
                    err_msg=f"k={k} p={p}")
                np.testing.assert_array_equal(
                    kept, np.isfinite(fused) & (fused > -1e8),
                    err_msg=f"fused k={k} p={p}")


def test_repetition_penalty_direction():
    logits = jnp.asarray([[2.0, -2.0, 1.0]])
    appeared = jnp.asarray([[True, True, False]])
    out = np.asarray(repetition_penalty_processor(logits, appeared, 2.0))
    assert out[0, 0] == 1.0       # positive divided
    assert out[0, 1] == -4.0      # negative multiplied
    assert out[0, 2] == 1.0       # untouched


def test_min_length_suppresses_eos():
    logits = jnp.zeros((1, 4))
    out = np.asarray(min_length_processor(logits, jnp.asarray(1), 3, 2))
    assert out[0, 2] < -1e8
    out2 = np.asarray(min_length_processor(logits, jnp.asarray(5), 3, 2))
    assert out2[0, 2] == 0.0


def test_generation_dp8_matches_single_device(model_and_params):
    """Distributed generation (generation_gpt_345M_dp8.yaml topology):
    the prompt batch sharded over a dp-8 mesh must sample exactly the
    single-device tokens — GSPMD partitions the same program."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 90, (8, 7)), jnp.int32)
    gen_cfg = GenerationConfig(
        max_dec_len=6, decode_strategy="greedy_search",
        eos_token_id=EOS, pad_token_id=PAD)
    single = np.asarray(generate(model, params, prompt, None,
                                 jax.random.key(1), gen_cfg))

    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("dp",))
    sharded_prompt = jax.device_put(
        prompt, NamedSharding(mesh, P("dp", None)))
    repl_params = jax.device_put(
        params, NamedSharding(mesh, P()))
    with mesh:
        dist = np.asarray(generate(model, repl_params, sharded_prompt,
                                   None, jax.random.key(1), gen_cfg))
    np.testing.assert_array_equal(dist, single)


def test_dp8_generation_config_parses():
    import os
    from paddlefleetx_tpu.utils.config import get_config
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = get_config(
        os.path.join(repo, "configs/nlp/gpt/generation_gpt_345M_dp8.yaml"),
        nranks=8)
    assert cfg.Distributed.dp_degree == 8
    assert cfg.Model.module == "GPTGenerationModule"
    assert cfg.Generation.top_k == 50
    inf = get_config(
        os.path.join(repo, "configs/nlp/gpt/inference_gpt_345M_dp8.yaml"),
        nranks=8)
    assert inf.Inference.mp_degree == 1
    assert inf.Data.Test.loader.collate_fn == "gpt_inference_collate_fn"


def test_hamming_diversity_matches_bincount_loop():
    """Penalty equals diversity_rate x per-batch bincount of earlier
    groups' tokens (reference processor.py:146-153 semantics)."""
    from paddlefleetx_tpu.models.gpt.processors import (
        hamming_diversity_processor,
    )
    rng = np.random.default_rng(0)
    batch, num_beams, groups, vocab = 2, 4, 2, 11
    sub = num_beams // groups
    tokens = jnp.asarray(rng.integers(0, vocab, batch * num_beams),
                         jnp.int32)
    scores = jnp.asarray(rng.normal(size=(batch * sub, vocab)),
                         jnp.float32)
    # group 0 is unpenalized
    np.testing.assert_array_equal(
        np.asarray(hamming_diversity_processor(
            scores, tokens, 0, 0.7, num_beams, groups)),
        np.asarray(scores))
    got = np.asarray(hamming_diversity_processor(
        scores, tokens, 1, 0.7, num_beams, groups))
    expect = np.asarray(scores).copy()
    toks = np.asarray(tokens)
    for b in range(batch):
        freq = np.bincount(toks[b * num_beams: b * num_beams + sub],
                           minlength=vocab)
        expect[b * sub:(b + 1) * sub] -= 0.7 * freq
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_hamming_diversity_validation():
    from paddlefleetx_tpu.models.gpt.processors import (
        hamming_diversity_processor,
    )
    s = jnp.zeros((2, 5)); t = jnp.zeros((4,), jnp.int32)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="diversity_rate"):
        hamming_diversity_processor(s, t, 1, 0.0, 4, 2)
    with _pytest.raises(ValueError, match="num_beams"):
        hamming_diversity_processor(s, t, 1, 0.5, 1, 2)
    with _pytest.raises(ValueError, match="num_beam_groups"):
        hamming_diversity_processor(s, t, 1, 0.5, 4, 1)


def test_generation_tp4_matches_single_device(model_and_params):
    """Generation with mp-sharded params (vocab-sharded logits — the
    reference's GPTForGenerationHybrid parallel_matmul story) samples
    exactly the single-device tokens."""
    import flax.linen as nn
    from paddlefleetx_tpu.parallel import (
        TopologyConfig, build_mesh, make_sharding_rules,
    )

    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(0, 90, (4, 6)), jnp.int32)
    gen_cfg = GenerationConfig(
        max_dec_len=5, decode_strategy="greedy_search",
        eos_token_id=EOS, pad_token_id=PAD)
    single = np.asarray(generate(model, params, prompt, None,
                                 jax.random.key(2), gen_cfg))

    topo = TopologyConfig(mp_degree=4, dp_degree=2)
    mesh = build_mesh(topo)
    rules = make_sharding_rules(topo)
    logical = nn.get_partition_spec(
        jax.eval_shape(model.init, {"params": jax.random.key(0)},
                       jnp.zeros((1, 8), jnp.int32)))
    shardings = nn.logical_to_mesh_sharding(logical, mesh, list(rules))
    params_s = jax.device_put({"params": params},
                              nn.meta.unbox(shardings))["params"]
    with mesh, nn.logical_axis_rules(list(rules)):
        dist = np.asarray(generate(model, params_s, prompt, None,
                                   jax.random.key(2), gen_cfg))
    np.testing.assert_array_equal(dist, single)


def test_beam_search_processed_score_semantics_k_gt_1(model_and_params):
    """Pins the PROCESSED-score accumulation for real beam widths
    (ADVICE r2 #1 / VERDICT r3 #6): beam ranking is by cumulative
    log-softmax of the repetition-penalty-processed logits — HF /
    reference semantics — NOT raw model likelihood. Verified by an
    independent teacher-forced replay of the processor pipeline: the
    returned beams must be ordered by the replayed processed score,
    and a repetition penalty != 1 must CHANGE what the search returns
    versus the unpenalized run."""
    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(21).integers(0, 90, (3, 6)), jnp.int32)
    b0, plen, dec, k, nrs, pen = 3, prompt.shape[1], 5, 4, 2, 1.5

    def replay_processed_score(rows):
        """Cumulative processed log-prob of each returned row,
        replayed independently of the beam bookkeeping."""
        rows = jnp.asarray(rows)                      # [n, dec]
        n = rows.shape[0]
        src = jnp.repeat(prompt, nrs, axis=0)         # prompt per row
        full = jnp.concatenate([src, rows], axis=1)
        logits = model.apply({"params": params}, full).astype(
            jnp.float32)
        appeared = jnp.zeros((n, CFG.vocab_size), bool)
        appeared = appeared.at[jnp.arange(n)[:, None], src].set(True)
        total = jnp.zeros((n,), jnp.float32)
        for t in range(dec):
            step = repetition_penalty_processor(
                logits[:, plen - 1 + t, :], appeared, pen)
            step = min_length_processor(step, t, dec, EOS)
            lp = jax.nn.log_softmax(step, -1)
            tok = rows[:, t]
            total = total + lp[jnp.arange(n), tok]
            appeared = appeared.at[jnp.arange(n), tok].set(True)
        return np.asarray(total)

    # min_dec_len = dec bans EOS throughout: every hypothesis stays
    # live and the replay maps 1:1 (no length-penalized finished pool)
    kw = dict(max_dec_len=dec, min_dec_len=dec,
              decode_strategy="beam_search",
              num_beams=k, num_return_sequences=nrs,
              eos_token_id=EOS, pad_token_id=PAD)
    out_pen = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(repetition_penalty=pen, **kw)))
    out_raw = np.asarray(generate(
        model, params, prompt, None, jax.random.key(0),
        GenerationConfig(repetition_penalty=1.0, **kw)))
    assert out_pen.shape == (b0 * nrs, dec)
    assert not (out_pen == EOS).any() and not (out_raw == EOS).any()
    # (a) the penalty changes the returned hypotheses for >=1 prompt
    assert not np.array_equal(out_pen, out_raw)
    # (b) within each prompt the nrs returned beams are ordered by the
    # REPLAYED processed score (ties allowed)
    scores = replay_processed_score(out_pen).reshape(b0, nrs)
    assert (scores[:, :-1] >= scores[:, 1:] - 1e-4).all(), scores
    # (c) and that order really is the PROCESSED order, not raw
    # likelihood: for at least one prompt the returned order must
    # INVERT the raw teacher-forced log-prob order (a beam search that
    # ranked by raw likelihood would pass (b) only if the two orders
    # coincided everywhere)
    raw = np.zeros((b0 * nrs,))
    full = np.concatenate([np.repeat(np.asarray(prompt), nrs, 0),
                           out_pen], axis=1)
    logits = np.asarray(model.apply(
        {"params": params}, jnp.asarray(full)).astype(jnp.float32))
    lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))
    for t in range(dec):
        raw += lp[np.arange(b0 * nrs), plen - 1 + t, out_pen[:, t]]
    raw = raw.reshape(b0, nrs)
    assert (raw[:, 0] < raw[:, 1] - 1e-6).any(), (
        "raw and processed orders coincide for every prompt — the "
        "test lost its discriminating power; change the seed", raw)


def test_cache_capacity_rounds_up_to_128(model_and_params):
    """`GPTConfig.cache_capacity` = max_position_embeddings rounded UP
    to a multiple of 128 (TPU lane width / flash-decode block
    alignment), and the cache the model ALLOCATES uses it — an
    unaligned max_position_embeddings can never knock decode off the
    kernel path via the `skv % block_kv` rejection."""
    assert CFG.max_position_embeddings == 48
    assert CFG.cache_capacity == 128
    mk = lambda mpe: GPTConfig(vocab_size=96, hidden_size=32,
                               num_layers=2, num_attention_heads=4,
                               max_position_embeddings=mpe)
    assert mk(128).cache_capacity == 128
    assert mk(129).cache_capacity == 256
    assert mk(1024).cache_capacity == 1024
    # the allocated cache's minor dim is the rounded capacity
    model, params = model_and_params
    _, mods = model.apply({"params": params},
                          jnp.zeros((1, 4), jnp.int32),
                          use_cache=True, mutable=["cache"])
    leaves = [l for l in jax.tree.leaves(mods["cache"]) if l.ndim >= 4]
    assert leaves and all(l.shape[-1] == 128 for l in leaves)


def test_kv_page_size_validation_composes_with_capacity():
    """The paged-cache knobs must compose with `cache_capacity`:
    `kv_page_size` a multiple of 128 that divides the (already
    128-rounded) capacity, and `kv_pool_pages` at least
    `max_kv_pages + 1` (page 0 is the reserved null page AND one
    request must always fit so preemption can make progress)."""
    mk = lambda **kw: GPTConfig(vocab_size=96, hidden_size=32,
                                num_layers=2, num_attention_heads=4,
                                max_position_embeddings=512, **kw)
    # defaults: paging off, zero knobs valid
    cfg = mk()
    assert cfg.kv_page_size == 0 and cfg.kv_pool_pages == 0
    # a valid paged config and the derived page count
    cfg = mk(kv_page_size=128, kv_pool_pages=9)
    assert cfg.max_kv_pages == 4  # 512 / 128
    assert mk(kv_page_size=256, kv_pool_pages=3).max_kv_pages == 2
    with pytest.raises(ValueError):  # pool without a page size
        mk(kv_page_size=0, kv_pool_pages=8)
    with pytest.raises(ValueError):  # not a multiple of 128
        mk(kv_page_size=64, kv_pool_pages=16)
    with pytest.raises(ValueError):  # does not divide cache_capacity
        mk(kv_page_size=384, kv_pool_pages=4)
    with pytest.raises(ValueError):  # pool < max_kv_pages + 1
        mk(kv_page_size=128, kv_pool_pages=4)
    # rounding interplay: mpe 129 -> capacity 256 -> 2 pages of 128
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=129,
                    kv_page_size=128, kv_pool_pages=3)
    assert cfg.cache_capacity == 256 and cfg.max_kv_pages == 2


def test_spec_knob_validation():
    """The speculative-decoding knobs (`GenerationConfig.spec_method`
    / `spec_tokens`) validate at construction: only the shipped
    'ngram' draft source is accepted, at least one draft token must
    be requested, and beam search — which reorders the batch every
    step — cannot compose with speculation."""
    mk = lambda **kw: GenerationConfig(max_dec_len=8,
                                       eos_token_id=95,
                                       pad_token_id=95, **kw)
    # defaults: speculation off, knobs inert
    cfg = mk()
    assert cfg.spec_method is None and cfg.spec_tokens >= 1
    # a valid speculative config, both served strategies
    assert mk(spec_method="ngram", spec_tokens=4).spec_tokens == 4
    assert mk(decode_strategy="sampling", spec_method="ngram",
              spec_tokens=1).spec_method == "ngram"
    with pytest.raises(ValueError, match="spec_method"):
        mk(spec_method="draft_model")     # not shipped (yet)
    with pytest.raises(ValueError, match="spec_tokens"):
        mk(spec_method="ngram", spec_tokens=0)
    with pytest.raises(ValueError, match="spec"):
        mk(decode_strategy="beam_search", num_beams=2,
           spec_method="ngram")
    # spec_tokens only validates when speculation is ON — the default
    # config never trips on it
    assert mk(spec_tokens=0).spec_method is None


def test_beam_gather_cache_reorders_under_mp_mesh(model_and_params):
    """Beam search's `_gather_cache` batch reordering must commute
    with an mp mesh whose cache leaves are sharded over heads (the
    `act_heads` plane): the gathered sharded cache equals the gathered
    replicated cache leaf-for-leaf."""
    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlefleetx_tpu.models.gpt.generation import _gather_cache
    from paddlefleetx_tpu.parallel import (
        TopologyConfig, build_mesh, make_sharding_rules,
    )
    from paddlefleetx_tpu.parallel.mesh import MP_AXIS

    model, params = model_and_params
    ids = jnp.asarray(
        np.random.default_rng(9).integers(0, 90, (4, 6)), jnp.int32)
    _, mods = model.apply({"params": params}, ids, use_cache=True,
                          mutable=["cache"])
    cache = mods["cache"]
    gidx = jnp.asarray([2, 0, 3, 1], jnp.int32)
    want = jax.tree.map(lambda l: np.asarray(l),
                        _gather_cache(cache, gidx))

    topo = TopologyConfig(mp_degree=4, dp_degree=2)
    mesh = build_mesh(topo)
    rules = make_sharding_rules(topo)

    def _shard(leaf):
        if leaf.ndim >= 4:     # [b, h, d, S] KV: heads over mp
            spec = P(*([None] * (leaf.ndim - 4)), None, MP_AXIS)
        else:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    cache_s = jax.tree.map(_shard, cache)
    with mesh, nn.logical_axis_rules(list(rules)):
        got = jax.jit(_gather_cache)(cache_s, gidx)
    jax.tree.map(
        lambda w, g: np.testing.assert_array_equal(w, np.asarray(g)),
        want, got)


def test_beam_search_tp4_matches_single_device(model_and_params):
    """End-to-end: beam search under an mp4 mesh (sharded params AND
    the per-step `_gather_cache` reorder over the sharded cache)
    returns exactly the single-device hypotheses."""
    import flax.linen as nn

    from paddlefleetx_tpu.parallel import (
        TopologyConfig, build_mesh, make_sharding_rules,
    )

    model, params = model_and_params
    prompt = jnp.asarray(
        np.random.default_rng(8).integers(0, 90, (2, 5)), jnp.int32)
    gen_cfg = GenerationConfig(
        max_dec_len=4, decode_strategy="beam_search", num_beams=3,
        num_return_sequences=2, eos_token_id=EOS, pad_token_id=PAD)
    single = np.asarray(generate(model, params, prompt, None,
                                 jax.random.key(2), gen_cfg))

    topo = TopologyConfig(mp_degree=4, dp_degree=2)
    mesh = build_mesh(topo)
    rules = make_sharding_rules(topo)
    logical = nn.get_partition_spec(
        jax.eval_shape(model.init, {"params": jax.random.key(0)},
                       jnp.zeros((1, 8), jnp.int32)))
    shardings = nn.logical_to_mesh_sharding(logical, mesh, list(rules))
    params_s = jax.device_put({"params": params},
                              nn.meta.unbox(shardings))["params"]
    with mesh, nn.logical_axis_rules(list(rules)):
        dist = np.asarray(generate(model, params_s, prompt, None,
                                   jax.random.key(2), gen_cfg))
    np.testing.assert_array_equal(dist, single)


# -- device-resident decode loop units (fused multi-tick serving) ------


def test_ring_write_wraparound():
    """_ring_write lands tick j's values in column j % T — the fused
    loops never wrap in one launch, but the helper must stay total for
    any tick counter a caller carries across launches."""
    from paddlefleetx_tpu.models.gpt.generation import _ring_write
    buf = jnp.full((2, 3), -1, jnp.int32)
    for tick in range(7):                 # 7 writes into T=3: wraps 2x
        vals = jnp.full((2,), tick, jnp.int32)
        buf = _ring_write(buf, vals, jnp.int32(tick), 3)
    # col j holds the LAST tick congruent to j mod 3: [6, 4, 5]
    np.testing.assert_array_equal(
        np.asarray(buf), [[6, 4, 5], [6, 4, 5]])
    # rank-3 buffers (the verify window [slots, T, k+1]) wrap the same
    wbuf = jnp.zeros((2, 3, 4), jnp.int32)
    wbuf = _ring_write(wbuf, jnp.ones((2, 4), jnp.int32),
                       jnp.int32(5), 3)
    assert np.asarray(wbuf)[:, 2].tolist() == [[1] * 4] * 2
    assert np.asarray(wbuf)[:, :2].sum() == 0


def test_loop_exit_reason_units():
    """The exit-reason priority chain on hand-built SlotStates:
    finished beats budget beats host flag; inactive slots never trip
    an exit; with nothing pending a full-T run reads as BUDGET."""
    from paddlefleetx_tpu.models.gpt.generation import (
        LOOP_EXIT_BUDGET, LOOP_EXIT_FINISHED, LOOP_EXIT_HOST,
        _loop_exit_flags, _loop_exit_reason, init_slot_state,
    )
    gen_cfg = GenerationConfig(max_dec_len=4, eos_token_id=EOS,
                               pad_token_id=PAD)
    on = jnp.asarray([True, True])
    base = init_slot_state(2, CFG.vocab_size)._replace(active=on)
    z, h = jnp.int32(0), jnp.int32(1)

    fin = base._replace(finished=jnp.asarray([True, False]))
    bud = base._replace(dec_count=jnp.asarray([4, 1], jnp.int32))
    both = fin._replace(dec_count=jnp.asarray([4, 1], jnp.int32))
    assert int(_loop_exit_reason(fin, gen_cfg, z)) == \
        LOOP_EXIT_FINISHED
    assert int(_loop_exit_reason(bud, gen_cfg, z)) == LOOP_EXIT_BUDGET
    assert int(_loop_exit_reason(both, gen_cfg, h)) == \
        LOOP_EXIT_FINISHED                      # finished wins
    assert int(_loop_exit_reason(base, gen_cfg, h)) == LOOP_EXIT_HOST
    assert int(_loop_exit_reason(base, gen_cfg, z)) == \
        LOOP_EXIT_BUDGET                        # full-T fallback
    # a FINISHED slot whose dec_count also expired books as finished,
    # not budget, in the flags the cond() short-circuits on
    fin_any, bud_any = _loop_exit_flags(both, gen_cfg)
    assert bool(fin_any) and not bool(bud_any)
    # inactive slots are invisible to every exit condition
    idle = init_slot_state(2, CFG.vocab_size)._replace(
        finished=jnp.asarray([True, True]),
        dec_count=jnp.asarray([9, 9], jnp.int32))
    fin_any, bud_any = _loop_exit_flags(idle, gen_cfg)
    assert not bool(fin_any) and not bool(bud_any)


def test_slot_state_pytree_stable_under_loop_carry():
    """SlotState must thread a jitted lax.while_loop unchanged in
    pytree structure, leaf dtypes, and leaf shapes — the contract that
    lets decode_loop carry it across T ticks without recompiles."""
    from paddlefleetx_tpu.models.gpt.generation import init_slot_state
    state = init_slot_state(3, CFG.vocab_size)

    @jax.jit
    def roll(s):
        def body(carry):
            st, t = carry
            st = st._replace(dec_count=st.dec_count + 1,
                             lengths=st.lengths + 1)
            return st, t + 1
        s, _ = jax.lax.while_loop(lambda c: c[1] < 4, body,
                                  (s, jnp.int32(0)))
        return s

    out = roll(state)
    assert jax.tree_util.tree_structure(out) == \
        jax.tree_util.tree_structure(state)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(out)):
        assert a.shape == b.shape and a.dtype == b.dtype
    assert np.asarray(out.dec_count).tolist() == [4, 4, 4]
