"""Test harness: force an 8-device virtual CPU platform.

Multi-host/multi-chip semantics are tested without a pod by giving XLA
eight host devices (SURVEY.md section 4 implication). jax may already
be imported by site customization before this file runs, so the
platform/device-count knobs are set through jax.config as well as the
environment; both happen before any backend is initialized.
"""

from paddlefleetx_tpu.parallel.mesh import cpu_mesh_env

cpu_mesh_env(8)

import jax  # noqa: E402
import pytest  # noqa: E402

assert jax.device_count() == 8, jax.devices()


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    """The process-wide mesh default must not leak between tests."""
    from paddlefleetx_tpu.parallel.mesh import set_mesh
    yield
    set_mesh(None)


# -- quick tier --------------------------------------------------------
# `pytest -m "not slow"` is the fast feedback loop (<10 min); the full
# suite runs everything. Centralized here (not as scattered decorators)
# so the tier stays tunable against measured durations
# (`pytest --durations=60`). Every subsystem keeps at least one
# representative test in the quick tier; what moves out are the heavy
# integration round-trips: subprocess drivers (TIPC/scale-proof/
# launch), engine train-loop and checkpoint-topology round-trips, the
# Imagen U-Net stacks, and the big sharded-equivalence goldens.
_SLOW_PATTERNS = (
    # whole subprocess-driver files
    "test_tipc_scripts.py", "test_scale_proof.py", "test_launch.py",
    # imagen heavy stacks
    "test_imagen.py::test_sr_config_parses_and_trains_scaled",
    "test_imagen.py::test_imagen_trains_through_engine",
    "test_imagen.py::test_full_cascade_sample",
    "test_imagen.py::test_unet_forward_shape_and_conditioning",
    "test_imagen.py::test_imagen_fp16o2_runs_bf16_unet_fp32_params",
    "test_imagen.py::test_cascade_stage2_init_matches_training",
    # engine round-trips (fit/accumulation/save-load basics stay quick)
    "test_engine.py::test_checkpoint_restores_across_mesh_and_scan_toggle",
    "test_engine.py::test_checkpoint_restores_across_topologies",
    "test_engine.py::test_checkpoint_restores_across_scan_layers_toggle",
    "test_engine.py::test_profiler_window_writes_trace",
    "test_engine.py::test_epoch_run_mode_evaluates_at_epoch_end",
    "test_engine.py::test_async_checkpoint_save_then_resume",
    "test_engine.py::test_sigterm_preemption_saves_and_stops",
    "test_engine.py::test_sharding_offload_downgrades_on_cpu",
    # sharded-equivalence goldens with big meshes
    "test_ring_attention.py::test_ring_grads_match_dense",
    "test_ring_attention.py::test_context_parallel_gpt_matches_single_device",
    "test_pipeline.py::test_pipelined_matches_single_device",
    "test_pipeline.py::test_1f1b_uses_less_activation_memory_than_gpipe",
    "test_moe.py::test_ep_sharded_matches_single_device",
    "test_flash_attention.py::test_ring_with_flash_blocks_matches_dense",
    # model-level heavy goldens
    "test_gpt_model.py::test_recompute_granularities_same_loss_and_grads",
    "test_gpt_model.py::test_chunked_lm_loss_matches_unchunked",
    "test_generation.py::test_greedy_matches_argmax_unrolled",
    "test_ernie.py::test_ernie_trains_through_engine",
    "test_vit.py::test_vit_trains_through_engine",
    "test_quantization.py::test_qat_gpt_trains",
    "test_utils_extra.py::test_benchmark_driver_end_to_end",
    "test_auto_configs.py::test_auto_345M_trains_on_mesh",
    # second trim pass (measured quick-tier durations, r4): heavier
    # representatives whose semantics another quick test still covers
    "test_imagen.py::test_imagen_train_math_and_sampling",
    "test_imagen.py::test_lowres_cond_unet",
    "test_ring_attention.py::test_ulysses_cp_gpt_matches_single_device",
    "test_ring_attention.py::test_ulysses_composes_with_tp",
    "test_pipeline.py::test_pipelined_loss_weighting_matches_accumulation",
    "test_utils_extra.py::test_cached_path",
    "test_engine.py::test_sigterm_during_eval_breaks_out_and_saves",
    "test_engine.py::test_profiler_summary_printed",
    "test_moe.py::test_moe_generation_decodes",
    # r5: full offline executions of the decode/MoE bench paths
    "test_bench_harness.py::test_bench_generation_runs_offline",
    "test_bench_harness.py::test_bench_moe_runs_offline",
    # r6 (measured quick-tier durations): the heaviest remaining
    # round-trips, each still represented in the quick tier by a
    # lighter sibling or enforced by a named CI job — imagen keeps
    # its cascade/sampling tests, MoE its engine train step, the
    # measure_train harness its bf16-accum twin, kill-resume
    # determinism runs full-fidelity in the chaos-smoke CI job, and
    # the real-tree lint gate stays via test_real_tree_is_clean /
    # test_real_tree_clean_under_new_rules (the CLI/stats duplicates
    # re-lint the whole repo two more times)
    "test_imagen.py::test_imagen_trains_fsdp_sharded",
    "test_moe.py::test_all_tokens_dropped_is_pure_residual",
    "test_bench_harness.py::test_measure_train_dropout_rng_threading",
    "test_resilience.py::test_resume_determinism_after_injected_kill",
    "test_pfxlint.py::test_real_tree_suppression_counts_pinned",
    "test_pfxlint.py::test_cli_list_rules_and_clean_exit",
    "test_pfxlint.py::test_cli_stats_prints_per_rule_suppressions",
    # the 16-cell adapter-id-0 parity matrix recompiles the server per
    # cell; the single-cell pins in test_lora.py stay quick
    "test_lora.py::test_adapter_id0_parity_matrix",
)


def pytest_collection_modifyitems(config, items):
    # EXACT matching (no substrings): "file.py" marks the whole file,
    # "file.py::test_name" marks that test (any parametrization). A
    # future test whose name merely extends a listed one stays quick,
    # and dead patterns are reported instead of rotting silently.
    slow = pytest.mark.slow
    matched = set()
    for item in items:
        base = item.nodeid.split("[")[0]
        fname = base.split("::")[0].rsplit("/", 1)[-1]
        rest = base.split("::", 1)[1] if "::" in base else ""
        for p in _SLOW_PATTERNS:
            if (p.endswith(".py") and fname == p) or \
                    ("::" in p and (fname, rest) ==
                     tuple(p.split("::", 1))):
                item.add_marker(slow)
                matched.add(p)
                break
    # dead patterns are pinned statically by
    # test_docstring_checker.py::test_slow_tier_patterns_exist (a
    # runtime warning here would misfire on partial runs, where
    # unmatched patterns are legitimate)
