"""Test harness: force an 8-device virtual CPU platform.

Multi-host/multi-chip semantics are tested without a pod by giving XLA
eight host devices (SURVEY.md section 4 implication). jax may already
be imported by site customization before this file runs, so the
platform/device-count knobs are set through jax.config as well as the
environment; both happen before any backend is initialized.
"""

from paddlefleetx_tpu.parallel.mesh import cpu_mesh_env

cpu_mesh_env(8)

import jax  # noqa: E402
import pytest  # noqa: E402

assert jax.device_count() == 8, jax.devices()


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    """The process-wide mesh default must not leak between tests."""
    from paddlefleetx_tpu.parallel.mesh import set_mesh
    yield
    set_mesh(None)
