"""Run the driver's multi-chip dry run on a virtual CPU mesh.

Usage: ``python tests/run_dryrun.py [n_devices]`` (default 8). Forces
the CPU platform through jax.config before any backend initializes
(site customization may pin another platform via env), then executes
``__graft_entry__.dryrun_multichip`` — one real training step of the
full pp/tp/dp/fsdp/(cp) composite on tiny shapes.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    from paddlefleetx_tpu.parallel.mesh import cpu_mesh_env
    cpu_mesh_env(n)
    import __graft_entry__
    __graft_entry__.dryrun_multichip(n)
