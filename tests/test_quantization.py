"""QAT: fake-quant math, STE gradients, kernel-only param transform,
and a quantized GPT training run."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.ops.quantization import (
    QuantizationConfig, fake_quant, qat_apply, quantize_params,
)


def test_fake_quant_levels():
    x = jnp.linspace(-1.0, 1.0, 11)
    q = fake_quant(x, bits=8)
    # max magnitude preserved, values on the int8 grid scaled back
    np.testing.assert_allclose(float(jnp.max(jnp.abs(q))), 1.0,
                               rtol=1e-6)
    scale = 1.0 / 127
    np.testing.assert_allclose(np.asarray(q) / scale,
                               np.round(np.asarray(q) / scale),
                               atol=1e-4)
    # 8-bit quantization error bounded by half a level
    assert float(jnp.max(jnp.abs(q - x))) <= scale / 2 + 1e-6


def test_fake_quant_ste_gradient():
    g = jax.grad(lambda x: jnp.sum(fake_quant(x) ** 2))(
        jnp.asarray([0.3, -0.7, 1.0]))
    # straight-through: d/dx sum(q^2) ~ 2q (identity through round)
    q = fake_quant(jnp.asarray([0.3, -0.7, 1.0]))
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(q),
                               rtol=1e-5)


def test_quantize_params_kernels_only():
    params = {
        "dense": {"kernel": jnp.asarray([[0.123456]]),
                  "bias": jnp.asarray([0.123456])},
        "norm": {"scale": jnp.asarray([0.999])},
    }
    out = quantize_params(params, bits=8)
    # kernel snapped to grid; bias/scale untouched
    assert float(out["dense"]["kernel"][0, 0]) == \
        float(fake_quant(params["dense"]["kernel"])[0, 0])
    assert float(out["dense"]["bias"][0]) == \
        float(params["dense"]["bias"][0])
    assert float(out["norm"]["scale"][0]) == \
        float(params["norm"]["scale"][0])


def test_stacked_kernel_per_layer_scale():
    """Scan-stacked [L, in, out] kernels get one scale per layer: a
    tiny-magnitude layer keeps its resolution instead of inheriting
    the largest layer's scale (reference paddleslim quantizes each
    Linear independently)."""
    big = np.full((4, 4), 100.0, np.float32)
    small = np.linspace(-0.01, 0.01, 16, dtype=np.float32) \
        .reshape(4, 4)
    stacked = jnp.asarray(np.stack([big, small]))
    params = {"decoder": {"fc": {"kernel": stacked}}}

    out = quantize_params(params, bits=8, stacked_module="decoder")
    got = out["decoder"]["fc"]["kernel"]
    # each layer matches an independent per-tensor fake_quant
    np.testing.assert_allclose(np.asarray(got[0]),
                               np.asarray(fake_quant(stacked[0])))
    np.testing.assert_allclose(np.asarray(got[1]),
                               np.asarray(fake_quant(stacked[1])))
    # and the small layer is NOT flattened to zero (shared-scale
    # quantization would round everything below 100/127/2 away)
    assert float(jnp.max(jnp.abs(got[1]))) > 0

    # without the stacked hint the shared scale destroys the layer
    shared = quantize_params(params, bits=8)["decoder"]["fc"]["kernel"]
    np.testing.assert_allclose(np.asarray(shared[1]), 0.0)


def test_from_config_warns_on_unknown_keys():
    """A typo'd Quantization key silently trains WITHOUT quantization
    (the reference's paddleslim would have raised) — from_config must
    warn loudly, naming the bad keys, and still build from the good
    ones."""
    import logging

    from paddlefleetx_tpu.utils.log import logger

    lines = []
    h = logging.Handler()
    h.emit = lambda rec: lines.append(rec.getMessage())
    logger.addHandler(h)
    try:
        cfg = QuantizationConfig.from_config(
            {"Quantization": {"enable": True, "wieght_bits": 4,
                              "onnx_format": True}})
    finally:
        logger.removeHandler(h)
    assert cfg.enable and cfg.weight_bits == 8  # typo ignored
    text = "\n".join(lines)
    assert "wieght_bits" in text and "onnx_format" in text
    assert "not recognized" in text
    # a clean section stays silent
    lines.clear()
    logger.addHandler(h)
    try:
        QuantizationConfig.from_config(
            {"Quantization": {"enable": True, "weight_bits": 8}})
    finally:
        logger.removeHandler(h)
    assert not lines


def test_qat_gpt_trains(tmp_path):
    """QAT-enabled GPT through the engine: loss finite and decreasing,
    quantized forward close to the fp forward."""
    from paddlefleetx_tpu.core import Engine
    from paddlefleetx_tpu.data import build_dataloader
    from paddlefleetx_tpu.models import build_module
    from test_data import make_corpus
    from test_engine import tiny_config

    make_corpus(tmp_path, n_docs=40, doc_len_range=(20, 60), vocab=128,
                eos=127)
    cfg = tiny_config(tmp_path, **{"Engine.max_steps": 10,
                                   "Engine.logging_freq": 5})
    cfg["Quantization"] = {"enable": True, "weight_bits": 8,
                           "activation_bits": 8}
    module = build_module(cfg)
    assert module.qat_cfg.enable
    engine = Engine(cfg, module, mode="train")
    loader = build_dataloader(cfg.Data, "Train", num_replicas=1, rank=0)
    loader.batch_sampler.batch_size = cfg.Global.global_batch_size

    losses = []
    orig = module.training_step_end

    def capture(log):
        losses.append(log["loss"])
        orig(log)

    module.training_step_end = capture
    engine.fit(epoch=1, train_data_loader=loader)
    assert len(losses) == 2
    assert np.isfinite(losses[-1]) and losses[-1] < np.log(128)

    # 8-bit sim forward stays close to fp forward
    ids = jnp.zeros((2, 16), jnp.int32)
    fp = module.model.apply({"params": engine.state["params"]}, ids,
                            deterministic=True)
    q = qat_apply(module.model, QuantizationConfig(enable=True),
                  engine.state["params"], ids, deterministic=True)
    assert float(jnp.mean(jnp.abs(fp - q))) < 0.1 * float(
        jnp.mean(jnp.abs(fp)) + 1e-6)
