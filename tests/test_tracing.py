"""Tracing, rotation, exporters, and the live /metrics endpoint.

Covers the PR-10 observability substrate end to end below the serving/
engine integration level (which `tests/test_serving.py` and
`tests/test_observability.py` pin): span record grammar and lifecycle
through the flight recorder, size-capped recorder rotation, the
Prometheus text exposition and Perfetto/Chrome trace renderers, and an
HTTP round-trip against a `MetricsServer` on an ephemeral port —
including the /healthz ok -> draining 503 flip drain relies on.
"""

import json
import re
import urllib.error
import urllib.request

import pytest

from paddlefleetx_tpu.observability import export
from paddlefleetx_tpu.observability import metrics
from paddlefleetx_tpu.observability import server as obs_server
from paddlefleetx_tpu.observability import timeline as obs_timeline
from paddlefleetx_tpu.observability.recorder import (
    FlightRecorder, read_events, read_tail)
from paddlefleetx_tpu.observability.spans import NULL_SPAN, Span, Tracer

_HEX16 = re.compile(r"^[0-9a-f]{16}$")
_HEX8 = re.compile(r"^[0-9a-f]{8}$")


def _recorded(tmp_path, name="events.jsonl"):
    path = str(tmp_path / name)
    return FlightRecorder(path), path


# -- span lifecycle ----------------------------------------------------


def test_span_lifecycle_records_full_tree(tmp_path):
    rec, path = _recorded(tmp_path)
    tracer = Tracer(rec)
    assert tracer.enabled

    root = tracer.start_trace("serving/request", request="r0",
                              prompt_len=7)
    child = root.start_span("serving/queue")
    root.span_point("serving/first_token", ttft_ms=12.5)
    root.complete_span("engine/compile", 0.25, step=3)
    child.end(reason="admitted")
    root.end(tokens=4)
    rec.close()

    evs = read_events(path)
    by_kind = {}
    for e in evs:
        by_kind.setdefault(e["event"], []).append(e)

    begins = by_kind["span_begin"]
    assert [e["name"] for e in begins] == ["serving/request",
                                          "serving/queue"]
    troot, tchild = begins
    # id grammar: 16-hex trace, 8-hex spans; child links to parent on
    # the same trace
    assert _HEX16.match(troot["trace"])
    assert _HEX8.match(troot["span"])
    assert tchild["trace"] == troot["trace"]
    assert tchild["parent"] == troot["span"]
    assert troot["request"] == "r0" and troot["prompt_len"] == 7

    point = by_kind["span_point"][0]
    assert point["name"] == "serving/first_token"
    assert point["parent"] == troot["span"]
    assert point["ttft_ms"] == 12.5

    complete = by_kind["span"][0]
    assert complete["name"] == "engine/compile"
    assert complete["parent"] == troot["span"]
    assert complete["dur_ms"] == pytest.approx(250.0)
    assert _HEX8.match(complete["span"])

    ends = {e["name"]: e for e in by_kind["span_end"]}
    assert ends["serving/queue"]["span"] == tchild["span"]
    assert ends["serving/queue"]["reason"] == "admitted"
    assert ends["serving/request"]["tokens"] == 4
    assert ends["serving/request"]["dur_ms"] >= \
        ends["serving/queue"]["dur_ms"] >= 0.0
    # the whole timeline is time-ordered as written
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)


def test_span_end_is_idempotent_and_context_managed(tmp_path):
    rec, path = _recorded(tmp_path)
    tracer = Tracer(rec)
    with tracer.start_trace("engine/fit") as root:
        with root.start_span("engine/step", step=1):
            pass
    root.end()         # second end: must not re-emit
    root.end(extra=1)
    rec.close()
    evs = read_events(path)
    assert sum(e["event"] == "span_end" for e in evs) == 2


def test_explicit_trace_id_links_resumed_request(tmp_path):
    rec, path = _recorded(tmp_path)
    tracer = Tracer(rec)
    first = tracer.start_trace("serving/request")
    first.end()
    resumed = tracer.start_trace("serving/request",
                                 trace_id=first.trace_id, resumed=True)
    resumed.end()
    rec.close()
    begins = [e for e in read_events(path) if e["event"] == "span_begin"]
    assert begins[0]["trace"] == begins[1]["trace"]
    assert begins[1]["resumed"] is True
    # distinct span ids: same timeline, two request lifetimes
    assert begins[0]["span"] != begins[1]["span"]


def test_null_tracer_costs_nothing_and_never_emits(tmp_path):
    tracer = Tracer(None)
    assert not tracer.enabled
    span = tracer.start_trace("serving/request")
    assert span is NULL_SPAN
    assert span.start_span("serving/queue") is NULL_SPAN
    span.span_point("serving/first_token")
    span.complete_span("engine/compile", 1.0)
    span.end(tokens=3)
    with span:
        pass
    assert span.trace_id is None and span.span_id is None
    assert not list(tmp_path.iterdir())   # nothing written anywhere


def test_span_direct_construction_parent_grammar(tmp_path):
    rec, path = _recorded(tmp_path)
    tracer = Tracer(rec)
    s = Span(tracer, "engine/step", trace_id="ab" * 8)
    assert s.parent_id is None
    c = s.start_span("engine/h2d")
    assert c.parent_id == s.span_id
    c.end()
    s.end()
    rec.close()
    begins = [e for e in read_events(path) if e["event"] == "span_begin"]
    assert "parent" not in begins[0]       # roots carry no parent field
    assert begins[1]["parent"] == begins[0]["span"]


# -- recorder rotation -------------------------------------------------


def test_recorder_rotates_once_at_cap(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = FlightRecorder(path, max_bytes=2000)
    for i in range(200):
        rec.emit("filler", i=i, pad="x" * 40)
    rec.close()

    rolled = tmp_path / "events.jsonl.1"
    assert rolled.exists()
    # only ONE roll file ever exists; the live file restarted small
    assert not (tmp_path / "events.jsonl.2").exists()
    # first record of the live segment after a roll is the rotation
    # marker, carrying where the bytes went
    first_live = _parse_file(path)[0]
    assert first_live["event"] == "recorder_rotated"
    assert first_live["rotated_to"] == path + ".1"
    assert first_live["rotated_bytes"] >= 2000


def _parse_file(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_rotation_aware_readers_span_the_roll(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = FlightRecorder(path, max_bytes=600)
    for i in range(40):
        rec.emit("tick", i=i)
    rec.close()

    evs = read_events(path)
    seen = [e["i"] for e in evs if e["event"] == "tick"]
    # every record since the LAST roll plus the whole rolled file is
    # readable, in order, with no duplicates
    assert seen == sorted(set(seen))
    assert seen[-1] == 39
    assert any(e["event"] == "recorder_rotated" for e in evs)

    # a tail bigger than the live file continues into <path>.1
    n_live = len(_parse_file(path))
    t = read_tail(path, n_live + 5)
    assert len(t) == n_live + 5
    assert t[-1]["i"] == 39
    assert [e["ts"] for e in t] == sorted(e["ts"] for e in t)


def test_recorder_env_knob_and_default(monkeypatch, tmp_path):
    monkeypatch.delenv("PFX_RECORDER_MAX_BYTES", raising=False)
    rec = FlightRecorder(str(tmp_path / "a.jsonl"))
    assert rec.max_bytes == 64 * 1024 * 1024
    rec.close()
    monkeypatch.setenv("PFX_RECORDER_MAX_BYTES", "12345")
    rec = FlightRecorder(str(tmp_path / "b.jsonl"))
    assert rec.max_bytes == 12345
    rec.close()
    monkeypatch.setenv("PFX_RECORDER_MAX_BYTES", "not-a-number")
    rec = FlightRecorder(str(tmp_path / "c.jsonl"))
    assert rec.max_bytes == 64 * 1024 * 1024
    rec.close()


# -- Prometheus exposition --------------------------------------------

#: one valid 0.0.4 sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{le="[^"]+"\})? '
    r'[-+]?[0-9.e+-]+(inf)?$')


def test_prometheus_text_grammar_and_content():
    reg = metrics.MetricsRegistry(enabled=True)
    reg.inc("serving/requests", 3)
    reg.set_gauge("serving/occupancy", 2)
    reg.set_gauge("serving/label", "not-a-number")   # must be skipped
    reg.add_time("engine/step", 1.5)
    for v in (1.0, 5.0, 9.0, 250.0):
        reg.observe("serving/ttft_ms", v)

    body = export.prometheus_text([reg])
    assert body.endswith("\n")
    lines = body.splitlines()
    for line in lines:
        assert line.startswith("# TYPE ") or _SAMPLE_RE.match(line), \
            f"bad exposition line: {line!r}"

    assert "# TYPE pfx_serving_requests_total counter" in lines
    assert "pfx_serving_requests_total 3.0" in lines
    assert "# TYPE pfx_serving_occupancy gauge" in lines
    assert "pfx_serving_occupancy 2.0" in lines
    assert "# TYPE pfx_engine_step_seconds_total counter" in lines
    assert "pfx_engine_step_seconds_total 1.5" in lines
    assert "# TYPE pfx_serving_ttft_ms histogram" in lines
    assert not any("label" in ln for ln in lines)

    # histogram: cumulative non-decreasing buckets, +Inf == count
    buckets = [ln for ln in lines
               if ln.startswith("pfx_serving_ttft_ms_bucket")]
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert cums == sorted(cums)
    assert buckets[-1].startswith('pfx_serving_ttft_ms_bucket{le="+Inf"}')
    assert cums[-1] == 4
    assert "pfx_serving_ttft_ms_count 4" in lines
    assert "pfx_serving_ttft_ms_sum 265.0" in lines


def test_prometheus_text_merges_registries():
    a = metrics.MetricsRegistry(enabled=True)
    b = metrics.MetricsRegistry(enabled=True)
    a.inc("shared/n", 2)
    b.inc("shared/n", 5)
    a.set_gauge("g/x", 1)
    b.set_gauge("g/x", 9)
    lines = export.prometheus_text([a, b]).splitlines()
    assert "pfx_shared_n_total 7.0" in lines   # counters sum
    assert "pfx_g_x 9.0" in lines              # gauges last-wins


def test_merge_snapshots_for_vars():
    a = metrics.MetricsRegistry(enabled=True)
    b = metrics.MetricsRegistry(enabled=True)
    a.inc("n", 1)
    b.inc("n", 2)
    a.add_time("t", 0.5)
    b.add_time("t", 0.25)
    b.observe("h/x_ms", 3.0)
    out = export.merge_snapshots([a.snapshot(), b.snapshot()])
    assert out["counters"]["n"] == 3
    assert out["timers"]["t"] == pytest.approx(0.75)
    assert out["histograms"]["h/x_ms"]["count"] == 1
    json.dumps(out)   # /vars must be JSON-serializable


# -- Perfetto / Chrome trace JSON -------------------------------------


def test_chrome_trace_shapes_and_json_validity(tmp_path):
    rec, path = _recorded(tmp_path)
    tracer = Tracer(rec)
    r1 = tracer.start_trace("serving/request")
    q = r1.start_span("serving/queue")
    r1.span_point("serving/first_token")
    q.end()
    r1.complete_span("engine/compile", 0.1)
    r1.end()
    r2 = tracer.start_trace("serving/request")
    r2.end()
    rec.emit("serving_admit", request="r9")   # non-span: skipped
    rec.close()

    trace = export.chrome_trace(read_events(path))
    blob = json.dumps(trace)                  # Perfetto-loadable JSON
    assert json.loads(blob)["displayTimeUnit"] == "ms"
    evs = trace["traceEvents"]
    phases = [e["ph"] for e in evs]
    # metadata: ONE process_name row (pid 1 = "requests") plus one
    # thread_name row per trace id => per track, tids stable over the
    # sorted trace ids
    meta = [e for e in evs if e["ph"] == "M"]
    assert phases.count("M") == 3
    assert all(e["pid"] == 1 for e in meta)
    pname = [e for e in meta if e["name"] == "process_name"]
    assert len(pname) == 1 and pname[0]["args"]["name"] == "requests" \
        and pname[0]["tid"] == 0
    tmeta = [e for e in meta if e["name"] == "thread_name"]
    assert {e["args"]["name"] for e in tmeta} == \
        {f"trace {r1.trace_id}", f"trace {r2.trace_id}"}
    assert {e["tid"] for e in tmeta} == {1, 2}
    assert [e["tid"] for e in tmeta] == \
        [t for _, t in sorted((e["args"]["name"], e["tid"])
                              for e in tmeta)]   # sorted-id order
    # begins pair with ends; the complete span is one X with dur
    assert phases.count("B") == phases.count("E") == 3
    x = [e for e in evs if e["ph"] == "X"]
    assert len(x) == 1 and x[0]["dur"] == pytest.approx(100.0 * 1e3)
    assert x[0]["name"] == "engine/compile"
    inst = [e for e in evs if e["ph"] == "i"]
    assert len(inst) == 1 and inst[0]["s"] == "t"
    # the non-span serving_admit record must not leak into the trace
    assert all(e["name"] != "serving_admit" for e in evs)
    # timestamps are microseconds (wall-clock seconds * 1e6)
    b0 = next(e for e in evs if e["ph"] == "B")
    assert b0["ts"] > 1e15


def test_chrome_trace_merges_timeline_tracks(tmp_path):
    rec, path = _recorded(tmp_path)
    r1 = Tracer(rec).start_trace("serving/request")
    r1.end()
    rec.close()

    snap = {
        "zz-worker-1": [("tick", 10.0, 10.5, r1.trace_id),
                        ("idle", 10.5, 10.6, None)],
        "aa-writer": [("handoff_host", 10.1, 10.2, r1.trace_id)],
    }
    trace = export.chrome_trace(read_events(path), timeline=snap)
    json.dumps(trace)                         # Perfetto-loadable
    evs = trace["traceEvents"]
    # the two processes are named and disjoint by pid
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e.get("name") == "process_name"}
    assert pnames == {1: "requests", 2: "threads"}
    tmeta = [e for e in evs
             if e.get("name") == "thread_name" and e["pid"] == 2]
    # one thread row per track, tids 1..M over SORTED track names
    assert [(e["tid"], e["args"]["name"]) for e in tmeta] == \
        [(1, "aa-writer"), (2, "zz-worker-1")]
    slices = [e for e in evs if e["ph"] == "X" and e["pid"] == 2]
    assert len(slices) == 3
    tick = next(e for e in slices if e["name"] == "tick")
    assert tick["tid"] == 2
    assert tick["ts"] == pytest.approx(10.0 * 1e6)
    assert tick["dur"] == pytest.approx(0.5 * 1e6)
    # trace-tagged intervals carry the request's trace id; untagged
    # ones carry no args noise
    assert tick["args"] == {"trace": r1.trace_id}
    idle = next(e for e in slices if e["name"] == "idle")
    assert idle["args"] == {}
    # span rows never leak into the threads pid
    assert all(e["pid"] == 1 for e in evs if e["ph"] in ("B", "E"))
    # without a timeline snapshot the threads process is absent
    bare = export.chrome_trace(read_events(path))
    assert all(e["pid"] == 1 for e in bare["traceEvents"])


# -- the live HTTP server ---------------------------------------------


def _get(url):
    """(status, content_type, body) for a GET, errors included."""
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return (resp.status, resp.headers.get("Content-Type", ""),
                    resp.read().decode("utf-8"))
    except urllib.error.HTTPError as err:
        return (err.code, err.headers.get("Content-Type", ""),
                err.read().decode("utf-8"))


def test_metrics_server_http_roundtrip(tmp_path):
    # names no production code emits: the server always merges the
    # process-global registry in, and suite-order must not matter
    reg = metrics.MetricsRegistry(enabled=True)
    reg.inc("tt/requests", 2)
    reg.observe("tt/lat_ms", 7.0)
    rec, events_path = _recorded(tmp_path)
    Tracer(rec).start_trace("serving/request").end()
    rec.close()

    health = {"status": "ok", "slots": 4}
    srv = obs_server.MetricsServer(
        port=0, registries=[reg], health=lambda: dict(health),
        events_path=events_path)
    try:
        assert srv.port > 0    # ephemeral port resolved

        code, ctype, body = _get(srv.url("/metrics"))
        assert code == 200 and ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        assert "pfx_tt_requests_total 2.0" in body
        assert 'pfx_tt_lat_ms_bucket{le="+Inf"} 1' in body

        code, ctype, body = _get(srv.url("/vars"))
        assert code == 200 and ctype.startswith("application/json")
        snap = json.loads(body)
        assert snap["counters"]["tt/requests"] == 2
        assert snap["histograms"]["tt/lat_ms"]["count"] == 1

        code, _, body = _get(srv.url("/healthz"))
        assert code == 200 and json.loads(body)["status"] == "ok"
        health["status"] = "draining"       # the drain() flip
        code, _, body = _get(srv.url("/healthz"))
        assert code == 503
        assert json.loads(body)["status"] == "draining"

        code, _, body = _get(srv.url("/trace"))
        assert code == 200
        trace = json.loads(body)
        assert any(e.get("ph") == "B" for e in trace["traceEvents"])

        code, _, _ = _get(srv.url("/nope"))
        assert code == 404
    finally:
        srv.close()
    srv.close()     # idempotent


def test_metrics_server_without_events_stream(tmp_path):
    srv = obs_server.MetricsServer(port=0)
    try:
        code, _, _ = _get(srv.url("/trace"))
        assert code == 404                   # no stream attached
        code, _, body = _get(srv.url("/healthz"))
        assert code == 200                   # default health is ok
        assert json.loads(body)["status"] == "ok"
    finally:
        srv.close()


def test_timeline_endpoint_and_trace_merge(tmp_path):
    rec, events_path = _recorded(tmp_path)
    root = Tracer(rec).start_trace("serving/request")
    root.end()
    rec.close()

    obs_timeline.set_enabled(True)
    srv = obs_server.MetricsServer(port=0, events_path=events_path)
    try:
        tl = obs_timeline.track("tt-endpoint-worker")
        t0 = tl.begin()
        tl.add("tick", t0, trace=root.trace_id)

        code, ctype, body = _get(srv.url("/timeline"))
        assert code == 200 and ctype.startswith("application/json")
        snap = json.loads(body)
        assert snap["enabled"] is True
        states = [iv[0] for iv in snap["tracks"]["tt-endpoint-worker"]]
        assert "tick" in states
        # the serving thread instruments itself: the GET above ran
        # under the shared pfx-metrics track
        util = snap["utilization"]
        assert util["tt-endpoint-worker"]["util"] == pytest.approx(1.0)
        assert "pfx-metrics" in snap["tracks"]

        # /trace now merges the thread tracks behind the span rows
        code, _, body = _get(srv.url("/trace"))
        assert code == 200
        evs = json.loads(body)["traceEvents"]
        assert any(e.get("name") == "process_name"
                   and e["args"]["name"] == "threads" for e in evs)
        assert any(e["ph"] == "X" and e["pid"] == 2
                   and e["name"] == "tick"
                   and e["args"].get("trace") == root.trace_id
                   for e in evs)
    finally:
        srv.close()
        obs_timeline.set_enabled(False)


def test_timeline_endpoint_reports_disabled(tmp_path):
    obs_timeline.set_enabled(False)   # earlier in-process runs may
    srv = obs_server.MetricsServer(port=0)   # have left it on
    try:
        code, _, body = _get(srv.url("/timeline"))
        assert code == 200
        snap = json.loads(body)
        # the endpoint stays up and truthful with recording off; the
        # tracks dict may retain intervals recorded while enabled
        # earlier in the process, so only the flag is pinned
        assert snap["enabled"] is False
        assert isinstance(snap["tracks"], dict)
    finally:
        srv.close()


def test_start_from_env_gating(monkeypatch, tmp_path):
    # unset / blank / unparseable: no server, no cost
    monkeypatch.delenv("PFX_METRICS_PORT", raising=False)
    assert obs_server.start_from_env() is None
    monkeypatch.setenv("PFX_METRICS_PORT", "  ")
    assert obs_server.start_from_env() is None
    monkeypatch.setenv("PFX_METRICS_PORT", "http")
    assert obs_server.start_from_env() is None
    assert obs_server.get_server() is None

    monkeypatch.setenv("PFX_METRICS_PORT", "0")
    reg = metrics.MetricsRegistry(enabled=True)
    reg.inc("x/y", 1)
    try:
        srv = obs_server.start_from_env(registry=reg)
        assert srv is not None and srv is obs_server.get_server()
        # second caller attaches to the SAME singleton
        again = obs_server.start_from_env(
            health=lambda: {"status": "ok"},
            events_path=str(tmp_path / "e.jsonl"))
        assert again is srv
        code, _, body = _get(srv.url("/metrics"))
        assert code == 200 and "pfx_x_y_total 1.0" in body
    finally:
        obs_server.stop()
    assert obs_server.get_server() is None
