import numpy as np
import pytest

from paddlefleetx_tpu.data import (
    BlendedGPTDataset,
    build_dataloader, gpt_collate_fn, GPTBatchSampler, GPTDataset,
    Pad, Stack, Tuple,
)
from paddlefleetx_tpu.data.dataset.gpt_dataset import (
    _build_doc_idx, _build_sample_idx_py, _build_shuffle_idx,
    get_train_valid_test_split_,
)
from paddlefleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer
from paddlefleetx_tpu.utils.config import AttrDict


def make_corpus(tmp_path, n_docs=20, doc_len_range=(5, 40), seed=0,
                vocab=1000, eos=50256, name="corpus"):
    """Synthetic {prefix}_ids.npy + {prefix}_idx.npz corpus."""
    rng = np.random.default_rng(seed)
    lens = rng.integers(*doc_len_range, n_docs).astype(np.int32)
    ids = rng.integers(0, vocab, int(lens.sum())).astype(np.int32)
    # sprinkle EOS at document ends
    pos = np.cumsum(lens) - 1
    ids[pos] = eos
    prefix = str(tmp_path / name)
    np.save(prefix + "_ids.npy", ids)
    np.savez(prefix + "_idx.npz", lens=lens)
    return prefix, ids, lens


def test_split_boundaries_sum_to_size():
    bounds = get_train_valid_test_split_([949, 50, 1], 1000)
    assert bounds[0] == 0 and bounds[-1] == 1000
    assert bounds == sorted(bounds)


def test_sample_idx_covers_contiguous_tokens():
    """Each sample spans exactly seq_len+1 tokens, overlapping by 1."""
    sizes = np.array([7, 11, 5, 13, 9], np.int32)
    docs = np.arange(5)
    doc_idx = _build_doc_idx(docs, 3, np.random.RandomState(0), False)
    tpe = int(sizes.sum())
    seq_len = 8
    sample_idx = _build_sample_idx_py(sizes, doc_idx, seq_len, 3, tpe)
    assert sample_idx.shape == ((3 * tpe - 1) // seq_len + 1, 2)
    # token-position arithmetic: walk and verify each row advances by
    # seq_len tokens in the flattened epoch stream
    flat_pos = []
    for di, off in sample_idx:
        consumed = int(np.sum(sizes[doc_idx[:di]]))
        flat_pos.append(consumed + int(off))
    deltas = np.diff(flat_pos)
    assert (deltas == seq_len).all()


def test_dataset_samples_and_loss_mask(tmp_path):
    prefix, ids, lens = make_corpus(tmp_path)
    ds = GPTDataset(str(tmp_path), [1, 0, 0], max_seq_len=16,
                    num_samples=10, mode="Train", build_data_file=True)
    assert len(ds) >= 10
    tokens, pos, labels, mask = ds[0]
    assert tokens.shape == (16,) and labels.shape == (16,)
    assert (pos == np.arange(16)).all()
    # labels are tokens shifted by one
    np.testing.assert_array_equal(tokens[1:], labels[:-1])
    # EOS masked out of the loss
    assert (mask[tokens == 50256] == 0).all()
    assert (mask[tokens != 50256] == 1).all()


def test_dataset_epoch_jitter_geometry(tmp_path):
    """tokens_per_epoch=75, seq=32, num_samples=70: the last epoch
    holds floor(T/s)+1 samples (floor jitter). The reference's assert
    (gpt_dataset.py:298) crashes on this geometry; ours must build and
    index every advertised sample."""
    lens = np.asarray([40, 35], np.int32)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 1000, int(lens.sum())).astype(np.int32)
    np.save(str(tmp_path / "c_ids.npy"), ids)
    np.savez(str(tmp_path / "c_idx.npz"), lens=lens)
    ds = GPTDataset(str(tmp_path), [1, 0, 0], max_seq_len=32,
                    num_samples=70, mode="Train", build_data_file=True)
    assert len(ds) >= 70
    for i in (0, 69, len(ds) - 1):
        tokens, pos, labels, mask = ds[i]
        assert tokens.shape == (32,)


def test_dataset_index_cache_reused(tmp_path):
    make_corpus(tmp_path)
    ds1 = GPTDataset(str(tmp_path), [1, 0, 0], 16, 10, "Train",
                     build_data_file=True)
    s1 = [ds1[i][0].copy() for i in range(3)]
    # second instance must load identical cached indices
    ds2 = GPTDataset(str(tmp_path), [1, 0, 0], 16, 10, "Train",
                     build_data_file=False)
    for i in range(3):
        np.testing.assert_array_equal(s1[i], ds2[i][0])


def test_batch_sampler_rank_partition():
    class _DS:
        def __len__(self):
            return 64
    samplers = [GPTBatchSampler(_DS(), batch_size=4, num_replicas=4,
                                rank=r) for r in range(4)]
    batches = [list(s) for s in samplers]
    # same number of batches per rank; indices disjoint within a block
    assert len({len(b) for b in batches}) == 1
    first_block = np.concatenate([b[0] for b in batches])
    assert sorted(first_block.tolist()) == list(range(16))


def test_batch_sampler_consumed_samples_resume():
    class _DS:
        def __len__(self):
            return 64
    full = list(GPTBatchSampler(_DS(), 4, 2, 0))
    resumed = list(GPTBatchSampler(_DS(), 4, 2, 0, consumed_samples=16))
    assert resumed == full[2:]


def test_collate_combinators():
    batch = [([1, 2], [3.0]), ([4, 5], [6.0])]
    tokens, vals = Tuple(Stack("int64"), Stack())(batch)
    assert tokens.dtype == np.int64 and tokens.shape == (2, 2)
    padded = Pad(pad_val=-1)([[1], [1, 2, 3]])
    assert padded.shape == (2, 3) and padded[0, 1] == -1
    with pytest.raises(ValueError):
        Tuple(Stack())(batch)  # field-count mismatch


def test_gpt_collate_on_real_samples(tmp_path):
    make_corpus(tmp_path)
    ds = GPTDataset(str(tmp_path), [1, 0, 0], 16, 8, "Train",
                    build_data_file=True)
    out = gpt_collate_fn([ds[0], ds[1]])
    assert [a.shape for a in out] == [(2, 16)] * 4


def test_build_dataloader_from_yaml_section(tmp_path):
    make_corpus(tmp_path)
    cfg = AttrDict({"Train": AttrDict({
        "dataset": AttrDict({"name": "GPTDataset",
                             "input_dir": str(tmp_path),
                             "split": [1, 0, 0], "max_seq_len": 16,
                             "num_samples": 16, "mode": "Train",
                             "build_data_file": True}),
        "sampler": AttrDict({"name": "GPTBatchSampler", "batch_size": 2,
                             "shuffle": False, "drop_last": True}),
        "loader": AttrDict({"num_workers": 1, "return_list": False,
                            "collate_fn": "gpt_collate_fn"}),
    })})
    loader = build_dataloader(cfg, "Train", num_replicas=2, rank=1)
    batches = list(loader)
    assert len(batches) == len(loader)
    assert batches[0][0].shape == (2, 16)


class _SquareDataset:
    """Picklable toy dataset with per-item CPU work."""

    def __init__(self, n, poison=None):
        self.n = n
        self.poison = poison

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if self.poison is not None and i == self.poison:
            raise RuntimeError(f"poisoned item {i}")
        return np.full((4,), i * i, np.int64)


def _stack_collate(batch):
    """Module-level (picklable) so the worker-process path really runs
    in processes instead of silently falling back to threads."""
    return np.stack(batch)


def _batches_of(n, bs):
    return [list(range(i, i + bs)) for i in range(0, n, bs)]


@pytest.mark.parametrize("num_workers", [1, 4])
def test_loader_deterministic_order(num_workers):
    """Batches arrive in sampler order whatever finishes first, and
    multi-process results equal the single-thread loader's exactly."""
    from paddlefleetx_tpu.data.loader import DataLoader
    ds = _SquareDataset(24)
    loader = DataLoader(ds, _batches_of(24, 4),
                        collate_fn=_stack_collate,
                        num_workers=num_workers)
    got = list(loader)
    assert len(got) == 6
    for k, batch in enumerate(got):
        np.testing.assert_array_equal(
            batch, np.stack([np.full((4,), (4 * k + j) ** 2, np.int64)
                             for j in range(4)]))


@pytest.mark.parametrize("num_workers", [1, 4])
def test_loader_worker_error_propagates(num_workers):
    """An exception raised inside a worker (thread or subprocess)
    re-raises in the consuming iterator, not silently dropped."""
    from paddlefleetx_tpu.data.loader import DataLoader
    ds = _SquareDataset(16, poison=9)
    loader = DataLoader(ds, _batches_of(16, 4),
                        collate_fn=_stack_collate,
                        num_workers=num_workers)
    with pytest.raises(RuntimeError, match="poisoned item 9"):
        list(loader)


@pytest.mark.parametrize("num_workers", [1, 4])
def test_loader_early_break_shuts_down(num_workers):
    """Breaking out of the iterator mid-epoch must not hang or leak —
    and the loader must be re-iterable afterwards."""
    from paddlefleetx_tpu.data.loader import DataLoader
    ds = _SquareDataset(64)
    loader = DataLoader(ds, _batches_of(64, 4),
                        collate_fn=_stack_collate,
                        num_workers=num_workers)
    for k, batch in enumerate(loader):
        if k == 1:
            break
    got = list(loader)           # fresh epoch, full and in order
    assert len(got) == 16
    np.testing.assert_array_equal(got[0][1], np.full((4,), 1, np.int64))


def test_loader_unpicklable_falls_back_to_threads():
    """A lambda collate_fn can't cross a process boundary; the loader
    must fall back to the threaded path and still deliver every batch
    in order rather than crash."""
    from paddlefleetx_tpu.data.loader import DataLoader
    ds = _SquareDataset(8)
    loader = DataLoader(ds, _batches_of(8, 4),
                        collate_fn=lambda b: np.stack(b),
                        num_workers=4)
    got = list(loader)
    assert len(got) == 2
    np.testing.assert_array_equal(got[1][0],
                                  np.full((4,), 16, np.int64))


def test_tokenizer_byte_fallback_roundtrip():
    tok = GPTTokenizer()
    text = "Hello, TPU world! éè"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    assert tok.eos_token_id == tok.vocab_size - 1


def test_tokenizer_bpe_merges(tmp_path):
    # tiny trained vocab: merge "he" then "hel"
    import json
    vocab = {c: i for i, c in enumerate("helo wrd")}
    vocab.update({"he": 8, "hel": 9, "<|endoftext|>": 10})
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text("h e\nhe l\n")
    tok = GPTTokenizer.from_pretrained(str(tmp_path))
    assert tok.tokenize("hello") == ["hel", "l", "o"]


def make_named_corpus(tmp_path, name, n_docs, vocab=1000, eos=50256,
                      seed=0):
    """A corpus under a specific prefix name (for blending tests)."""
    return make_corpus(tmp_path, n_docs=n_docs, doc_len_range=(10, 30),
                       seed=seed, vocab=vocab, eos=eos, name=name)[0]


class TestBlendedGPTDataset:
    """BlendedGPTDataset drives the native build_blending_indices
    helper end-to-end (the reference ships the C++ entry point but
    never calls it from Python)."""

    def _corpora(self, tmp_path):
        make_named_corpus(tmp_path, "aa", 40, seed=1)
        make_named_corpus(tmp_path, "bb", 40, seed=2)
        return tmp_path

    def test_blend_ratio_tracks_weights(self, tmp_path):
        d = BlendedGPTDataset(
            str(self._corpora(tmp_path)), [1, 0, 0], 16, 200, "Train",
            weights=[3, 1], build_data_file=True)
        assert len(d) == 200
        counts = np.bincount(d.dataset_index, minlength=2)
        np.testing.assert_allclose(counts / 200, [0.75, 0.25],
                                   atol=0.01)
        # the greedy interleave keeps every prefix of the stream
        # on-ratio (within one sample per dataset)
        run = np.cumsum(d.dataset_index == 0)
        pos = np.arange(1, 201)
        assert np.abs(run - 0.75 * pos).max() <= 1.5

    def test_samples_come_from_the_right_corpus(self, tmp_path):
        d = BlendedGPTDataset(
            str(self._corpora(tmp_path)), [1, 0, 0], 16, 60, "Train",
            weights=[1, 1], build_data_file=True)
        for i in (0, 7, 31, 59):
            ds, j = d.dataset_index[i], int(d.dataset_sample_index[i])
            expect = d.datasets[ds][j]
            got = d[i]
            for a, b in zip(got, expect):
                np.testing.assert_array_equal(a, b)

    def test_default_weights_proportional_to_tokens(self, tmp_path):
        make_named_corpus(tmp_path, "big", 60, seed=3)
        make_named_corpus(tmp_path, "small", 20, seed=4)
        d = BlendedGPTDataset(str(tmp_path), [1, 0, 0], 16, 100,
                              "Train", build_data_file=True)
        assert d.weights[0] > d.weights[1]  # "big" sorts first
        np.testing.assert_allclose(d.weights.sum(), 1.0)

    def test_weights_length_mismatch_raises(self, tmp_path):
        with pytest.raises(ValueError, match="weights"):
            BlendedGPTDataset(
                str(self._corpora(tmp_path)), [1, 0, 0], 16, 10,
                "Train", weights=[1, 2, 3], build_data_file=True)

    def test_builds_through_dataloader_registry(self, tmp_path):
        from paddlefleetx_tpu.data import build_dataset

        self._corpora(tmp_path)
        cfg = {"Train": {"dataset": {
            "name": "BlendedGPTDataset", "input_dir": str(tmp_path),
            "split": [1, 0, 0], "max_seq_len": 16, "num_samples": 20,
            "mode": "Train", "weights": [2, 1],
            "build_data_file": True}}}
        ds = build_dataset(cfg, "Train")
        assert len(ds) == 20
        sample = ds[0]
        assert len(sample) == 4 and len(sample[0]) == 16
