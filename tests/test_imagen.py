"""Imagen family: diffusion math, U-Net shapes/conditioning, criterion,
dataset, engine training, and sampling."""

import base64
import io
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.imagen import (
    GaussianDiffusionContinuousTimes, ImagenModel, imagen_criterion,
)
from paddlefleetx_tpu.models.imagen.modeling import ImagenConfig
from paddlefleetx_tpu.models.imagen.unet import Unet, UnetConfig

TINY_UNET = dict(dim=16, dim_mults=(1, 2), num_resnet_blocks=1,
                 layer_attns=(False, True),
                 layer_cross_attns=(False, True), attn_heads=2,
                 attn_dim_head=8, text_embed_dim=32, num_latents=4,
                 cross_embed_kernel_sizes=(3, 7))


def tiny_imagen(**kw):
    base = dict(unets=("Unet64_397M",), image_sizes=(16,),
                text_embed_dim=32, timesteps=8,
                unet_overrides=tuple(TINY_UNET.items()))
    base.update(kw)
    return ImagenModel(ImagenConfig(**base))


# -- diffusion math -----------------------------------------------------

def test_q_sample_preserves_signal_noise_split():
    sched = GaussianDiffusionContinuousTimes("cosine", 10)
    x = jnp.ones((2, 4, 4, 3))
    noise = jnp.zeros_like(x)
    t = jnp.asarray([0.0, 0.999])
    noisy, log_snr = sched.q_sample(x, t, noise)
    # t=0: alpha ~ 1 (signal passes); t~1: alpha ~ 0
    assert float(noisy[0].mean()) > 0.99
    assert abs(float(noisy[1].mean())) < 0.1
    assert float(log_snr[0]) > float(log_snr[1])


def test_predict_start_inverts_q_sample():
    sched = GaussianDiffusionContinuousTimes("cosine", 10)
    rng = jax.random.key(0)
    x = jax.random.normal(rng, (2, 4, 4, 3))
    noise = jax.random.normal(jax.random.key(1), x.shape)
    t = jnp.asarray([0.3, 0.7])
    noisy, _ = sched.q_sample(x, t, noise)
    back = sched.predict_start_from_noise(noisy, t, noise)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=1e-4, rtol=1e-4)


def test_posterior_at_tiny_gap_returns_xnext_near_xt():
    sched = GaussianDiffusionContinuousTimes("linear", 100)
    x_start = jnp.zeros((1, 2, 2, 3))
    x_t = jnp.ones((1, 2, 2, 3))
    t = jnp.asarray([0.5])
    mean, var, _ = sched.q_posterior(x_start, x_t, t,
                                     t_next=jnp.asarray([0.499]))
    assert np.all(np.isfinite(np.asarray(mean)))
    assert float(var[0, 0, 0, 0]) >= 0


def test_sampling_timesteps_cover_1_to_0():
    sched = GaussianDiffusionContinuousTimes("cosine", 5)
    pairs = sched.get_sampling_timesteps(batch=2)
    assert pairs.shape == (5, 2, 2)
    assert float(pairs[0, 0, 0]) == 1.0
    assert float(pairs[-1, 1, 0]) == 0.0


# -- criterion ----------------------------------------------------------

def test_criterion_p2_weighting():
    pred = jnp.ones((2, 4, 4, 3))
    target = jnp.zeros_like(pred)
    log_snr = jnp.asarray([0.0, 0.0])
    plain = imagen_criterion(pred, target, log_snr, 0.0)
    np.testing.assert_allclose(float(plain), 1.0, rtol=1e-6)
    weighted = imagen_criterion(pred, target, log_snr, 1.0,
                                p2_loss_weight_k=1.0)
    np.testing.assert_allclose(float(weighted), 0.5, rtol=1e-6)
    l1 = imagen_criterion(pred * 2, target, log_snr, 0.0,
                          name="l1_loss")
    np.testing.assert_allclose(float(l1), 2.0, rtol=1e-6)


# -- U-Net --------------------------------------------------------------

def test_unet_forward_shape_and_conditioning():
    cfg = UnetConfig(**TINY_UNET)
    unet = Unet(cfg)
    x = jnp.zeros((2, 16, 16, 3))
    t = jnp.zeros((2,))
    emb = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 32)),
                      jnp.float32)
    mask = jnp.ones((2, 6), jnp.int32)
    params = unet.init({"params": jax.random.key(0)}, x, t,
                       text_embeds=emb, text_mask=mask)["params"]
    out = unet.apply({"params": params}, x, t, text_embeds=emb,
                     text_mask=mask)
    assert out.shape == (2, 16, 16, 3)
    # zero-init final conv -> exactly zero prediction at init
    np.testing.assert_allclose(np.asarray(out), 0.0)

    # conditioning matters: different text embeds -> different output
    params2 = jax.tree.map(
        lambda p: p + 0.01 * np.random.default_rng(1).normal(
            size=p.shape).astype(np.float32), params)
    a = unet.apply({"params": params2}, x, t, text_embeds=emb,
                   text_mask=mask)
    b = unet.apply({"params": params2}, x, t, text_embeds=emb + 1.0,
                   text_mask=mask)
    assert not np.allclose(np.asarray(a), np.asarray(b))

    # cond_drop_mask=True reproduces the null-conditioned output
    drop = unet.apply({"params": params2}, x, t, text_embeds=emb,
                      text_mask=mask,
                      cond_drop_mask=jnp.ones((2,), bool))
    drop2 = unet.apply({"params": params2}, x, t,
                       text_embeds=emb + 5.0, text_mask=mask,
                       cond_drop_mask=jnp.ones((2,), bool))
    np.testing.assert_allclose(np.asarray(drop), np.asarray(drop2),
                               atol=1e-6)


def test_lowres_cond_unet():
    cfg = UnetConfig(lowres_cond=True, **TINY_UNET)
    unet = Unet(cfg)
    x = jnp.zeros((1, 16, 16, 3))
    t = jnp.zeros((1,))
    lr = jnp.zeros((1, 16, 16, 3))
    params = unet.init({"params": jax.random.key(0)}, x, t,
                       lowres_cond_img=lr,
                       lowres_noise_times=t)["params"]
    out = unet.apply({"params": params}, x, t, lowres_cond_img=lr,
                     lowres_noise_times=t)
    assert out.shape == (1, 16, 16, 3)


# -- full model ---------------------------------------------------------

def test_imagen_train_math_and_sampling():
    model = tiny_imagen()
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (2, 3, 16, 16)),
        jnp.float32)  # NCHW like the reference collate
    emb = jnp.asarray(np.random.default_rng(1).normal(size=(2, 6, 32)),
                      jnp.float32)
    mask = jnp.ones((2, 6), jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        images, emb, mask)
    pred, target, log_snr, gamma = model.apply(
        variables, images, emb, mask,
        rngs={"diffusion": jax.random.key(2)})
    assert pred.shape == (2, 16, 16, 3)
    assert target.shape == pred.shape and log_snr.shape == (2,)
    loss = imagen_criterion(pred, target, log_snr, gamma)
    assert np.isfinite(float(loss))

    out = model.apply(
        variables, 1, (2, 16, 16, 3), emb, mask,
        method="sample_stage", rngs={"diffusion": jax.random.key(3)})
    assert out.shape == (2, 16, 16, 3)
    assert 0.0 <= float(out.min()) and float(out.max()) <= 1.0


def test_imagen_cascade_second_stage():
    model = tiny_imagen(unets=("Unet64_397M", "Unet64_397M"),
                        image_sizes=(8, 16))
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (2, 3, 16, 16)),
        jnp.float32)
    emb = jnp.zeros((2, 6, 32), jnp.float32)
    mask = jnp.ones((2, 6), jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        images, emb, mask, unet_number=2)
    pred, target, log_snr, _ = model.apply(
        variables, images, emb, mask, unet_number=2,
        rngs={"diffusion": jax.random.key(2)})
    assert pred.shape == (2, 16, 16, 3)


def test_standalone_sr_model_trains():
    """lowres_cond single-unet models (imagen_SR256-style) synthesize
    their conditioning image from the training batch."""
    model = tiny_imagen(
        unet_overrides=tuple({**TINY_UNET, "lowres_cond": True}.items()))
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (2, 3, 16, 16)),
        jnp.float32)
    emb = jnp.zeros((2, 6, 32), jnp.float32)
    mask = jnp.ones((2, 6), jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        images, emb, mask)
    pred, target, _, _ = model.apply(
        variables, images, emb, mask,
        rngs={"diffusion": jax.random.key(2)})
    assert pred.shape == target.shape == (2, 16, 16, 3)


def test_cascade_stage2_init_matches_training(tmp_path):
    """init_model_variables must create the SAME stage's params that
    loss_fn trains (unet_number threading)."""
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict({
        "Global": AttrDict({"device": "cpu", "seed": 1,
                            "global_batch_size": None,
                            "local_batch_size": 1,
                            "micro_batch_size": 1}),
        "Engine": AttrDict({"max_steps": 1,
                            "mix_precision": AttrDict({})}),
        "Model": AttrDict({
            "module": "ImagenModule",
            "name": "imagen_397M_text2im_64",
            "unet_number": 2,
            "unets": ("Unet64_397M", "Unet64_397M"),
            "image_sizes": (8, 16), "text_embed_dim": 32,
            "timesteps": 4,
            "unet_overrides": tuple(TINY_UNET.items()),
        }),
        "Loss": AttrDict({"name": "mse_loss"}),
        "Distributed": AttrDict({"dp_degree": 1, "sharding":
                                 AttrDict({})}),
        "Optimizer": AttrDict({"name": "Adam",
                               "lr": AttrDict({"learning_rate": 1e-4})}),
        "Data": AttrDict({}),
    })
    process_configs(cfg, nranks=1)
    module = build_module(cfg)
    samples = [jnp.zeros(tuple(1 if d is None else d for d in s),
                         jnp.dtype(t)) for s, t in module.input_spec()]
    variables = module.init_model_variables(
        module.model,
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        samples)
    assert "unet_1" in variables["params"]
    loss = module.loss_fn(
        variables["params"],
        (samples[0], samples[1], samples[2].astype(jnp.int32)),
        jax.random.key(2))
    assert np.isfinite(float(loss))


# -- dataset ------------------------------------------------------------

def _write_imagen_corpus(tmp_path, n=4):
    from PIL import Image
    rng = np.random.default_rng(0)
    data_dir = tmp_path / "data"
    os.makedirs(data_dir)
    lines = []
    for i in range(n):
        arr = rng.integers(0, 255, (40, 40, 3)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")
        b64 = base64.b64encode(buf.getvalue()).decode()
        embed = rng.normal(size=(6, 32)).astype(np.float32)
        np.save(data_dir / f"embed_{i}.npy", embed)
        np.save(data_dir / f"mask_{i}.npy", np.ones((6,), np.int64))
        lines.append(f"k{i}\tembed_{i}.npy\tmask_{i}.npy\t{b64}")
    tsv = data_dir / "part0.tsv"
    tsv.write_text("\n".join(lines))
    filelist = tmp_path / "filelist.txt"
    filelist.write_text(str(tsv) + "\n")
    return str(filelist)


def test_imagen_dataset(tmp_path):
    from paddlefleetx_tpu.data.dataset.multimodal_dataset import (
        ImagenDataset,
    )
    filelist = _write_imagen_corpus(tmp_path)
    ds = ImagenDataset(filelist, input_resolution=16, max_seq_len=8)
    assert len(ds) == 4
    image, embed, mask = ds[0]
    assert image.shape == (3, 16, 16)
    assert 0.0 <= image.min() and image.max() <= 1.0
    assert embed.shape == (8, 32) and mask.shape == (8,)
    assert mask[:6].all() and not mask[6:].any()


def test_imagen_trains_through_engine(tmp_path):
    from paddlefleetx_tpu.core import Engine
    from paddlefleetx_tpu.data import build_dataloader
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    filelist = _write_imagen_corpus(tmp_path, n=32)
    cfg = AttrDict({
        "Global": AttrDict({"device": "cpu", "seed": 2022,
                            "global_batch_size": None,
                            "local_batch_size": 1,
                            "micro_batch_size": 1}),
        "Engine": AttrDict({
            "max_steps": 4, "logging_freq": 2, "eval_freq": 1000,
            "mix_precision": AttrDict({}),
            "save_load": AttrDict({"save_steps": 1000,
                                   "output_dir": str(tmp_path / "o")}),
        }),
        "Model": AttrDict({
            "module": "ImagenModule",
            "name": "imagen_397M_text2im_64",
            "unet_number": 1,
            "image_sizes": (16,),
            "text_embed_dim": 32,
            "timesteps": 8,
            "unet_overrides": tuple(TINY_UNET.items()),
        }),
        "Loss": AttrDict({"name": "mse_loss", "p2_loss_weight_k": 1}),
        "Distributed": AttrDict({"dp_degree": 8, "mp_degree": 1,
                                 "pp_degree": 1,
                                 "sharding": AttrDict({})}),
        "Optimizer": AttrDict({
            "name": "Adam",
            "lr": AttrDict({"name": "CosineAnnealingWithWarmupDecay",
                            "decay_steps": 100, "warmup_rate": 0.1,
                            "max_lr": 1e-3, "min_lr": 1e-4}),
            "grad_clip": AttrDict({"clip_norm": 1.0}),
        }),
        "Data": AttrDict({"Train": AttrDict({
            "dataset": AttrDict({
                "name": "ImagenDataset", "input_path": filelist,
                "input_resolution": 16, "max_seq_len": 8}),
            "sampler": AttrDict({"name": "DistributedBatchSampler",
                                 "batch_size": 1, "shuffle": False,
                                 "drop_last": True}),
            "loader": AttrDict({"collate_fn": "imagen_collate_fn",
                                "num_workers": 1}),
        })}),
    })
    process_configs(cfg, nranks=8)
    module = build_module(cfg)
    engine = Engine(cfg, module, mode="train")
    loader = build_dataloader(cfg.Data, "Train", num_replicas=1, rank=0)
    loader.batch_sampler.batch_size = cfg.Global.global_batch_size

    losses = []
    orig = module.training_step_end

    def capture(log):
        losses.append(log["loss"])
        orig(log)

    module.training_step_end = capture
    engine.fit(epoch=1, train_data_loader=loader)
    assert len(losses) == 2
    assert all(np.isfinite(x) for x in losses)


# -- SR config tree -----------------------------------------------------

SR_YAMLS = ["imagen_super_resolution_256.yaml",
            "imagen_super_resolution_512.yaml",
            "imagen_super_resolution_1024.yaml"]


@pytest.mark.parametrize("fname", SR_YAMLS)
def test_sr_config_parses_and_trains_scaled(fname):
    """The SR YAMLs (reference imagen_super_resolusion_*.yaml) parse
    and their zoo entry takes a train step at scaled-down shape."""
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import get_config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = get_config(os.path.join(repo, "configs", "mm", "imagen", fname),
                     nranks=1)
    assert cfg.Model.name in ("imagen_SR256", "imagen_SR512",
                              "imagen_SR1024")
    assert cfg.Model.only_train_unet_number == 1
    # scale to test size: the SR unets keep their real topology
    # (memory_efficient, lowres_cond, per-level blocks) at tiny dims
    cfg.Model.image_sizes = [16]
    cfg.Model.text_embed_dim = 32
    cfg.Model.timesteps = 8
    cfg.Model.unet_overrides = {
        "dim": 16, "num_resnet_blocks": (1, 1, 1, 1), "attn_heads": 2,
        "attn_dim_head": 8, "text_embed_dim": 32, "num_latents": 4}
    module = build_module(cfg)
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (1, 3, 16, 16)),
        jnp.float32)
    emb = jnp.zeros((1, 6, 32), jnp.float32)
    mask = jnp.ones((1, 6), jnp.int32)
    variables = module.init_model_variables(
        module.model,
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        (images, emb, mask))
    bound = module.model.bind(variables)
    assert bound.unets[0].config.lowres_cond  # SR = conditioned
    assert bound.unets[0].config.memory_efficient
    loss, grads = jax.value_and_grad(module.loss_fn)(
        variables["params"], (images, emb, mask), jax.random.key(2))
    assert np.isfinite(float(loss))
    gnorm = jnp.sqrt(sum(jnp.sum(g ** 2) for g in
                         jax.tree_util.tree_leaves(grads)))
    assert np.isfinite(float(gnorm)) and float(gnorm) > 0


def test_per_sample_aug_noise_level():
    """per_sample_random_aug_noise_level=True draws one aug time per
    sample (reference knob in the SR configs)."""
    model = tiny_imagen(
        per_sample_random_aug_noise_level=True,
        unet_overrides=tuple({**TINY_UNET, "lowres_cond": True}.items()))
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (2, 3, 16, 16)),
        jnp.float32)
    emb = jnp.zeros((2, 6, 32), jnp.float32)
    mask = jnp.ones((2, 6), jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        images, emb, mask)
    pred, target, _, _ = model.apply(
        variables, images, emb, mask,
        rngs={"diffusion": jax.random.key(2)})
    assert pred.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(pred)).all()


def test_imagen_fp16o2_runs_bf16_unet_fp32_params():
    """AMP-O2 for imagen: the U-Net computes in bf16 (inputs cast at
    the call boundary, params cast in loss_fn) with fp32 masters."""
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict({
        "Global": AttrDict({"device": "cpu", "seed": 1,
                            "global_batch_size": None,
                            "local_batch_size": 1,
                            "micro_batch_size": 1}),
        "Engine": AttrDict({"max_steps": 1, "mix_precision":
                            AttrDict({"use_pure_fp16": True})}),
        "Model": AttrDict({
            "module": "ImagenModule", "name": "imagen_397M_text2im_64",
            "image_sizes": (16,), "text_embed_dim": 32, "timesteps": 4,
            "unet_overrides": tuple(TINY_UNET.items()),
        }),
        "Loss": AttrDict({"name": "mse_loss"}),
        "Distributed": AttrDict({"dp_degree": 1,
                                 "sharding": AttrDict({})}),
        "Optimizer": AttrDict({"name": "Adam",
                               "lr": AttrDict({"learning_rate": 1e-4})}),
        "Data": AttrDict({}),
    })
    process_configs(cfg, nranks=1)
    module = build_module(cfg)
    assert module.bf16_compute
    assert module.model.config.dtype == "bfloat16"
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (1, 3, 16, 16)),
        jnp.float32)
    emb = jnp.zeros((1, 6, 32), jnp.float32)
    mask = jnp.ones((1, 6), jnp.int32)
    variables = module.init_model_variables(
        module.model,
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        (images, emb, mask))
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32          # fp32 masters
    # the prediction comes back in the unet compute dtype
    cast = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating) else x,
        variables["params"])
    pred, target, _, _ = module.model.apply(
        {"params": cast}, images, emb, mask,
        rngs={"diffusion": jax.random.key(2)})
    assert pred.dtype == jnp.bfloat16             # bf16 compute
    # and the module-level loss is still a finite fp32 scalar
    loss = module.loss_fn(variables["params"], (images, emb, mask),
                          jax.random.key(3))
    assert loss.dtype == jnp.float32 and np.isfinite(float(loss))


def test_full_cascade_sample():
    """VERDICT r3 #3 (reference modeling.py:506-580): one sample()
    call walks the whole cascade, feeding each stage's output into
    the next stage's low-res conditioning, and returns the final
    resolution. Two tiny stages 8 -> 16."""
    model = tiny_imagen(unets=("Unet64_397M", "Unet64_397M"),
                        image_sizes=(8, 16))
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (2, 3, 16, 16)),
        jnp.float32)
    emb = jnp.asarray(np.random.default_rng(1).normal(size=(2, 6, 32)),
                      jnp.float32)
    mask = jnp.ones((2, 6), jnp.int32)
    # each stage trains (and initializes) separately, like the
    # reference's per-unet training; sampling needs both stages'
    # params merged — the checkpoint-merge a real cascade deploy does
    v1 = model.init(
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        images, emb, mask, unet_number=1)
    v2 = model.init(
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        images, emb, mask, unet_number=2)
    variables = {"params": {**v1["params"], **v2["params"]}}

    out = model.apply(
        variables, text_embeds=emb, text_masks=mask,
        cond_scale=(1.0, 3.0),  # per-stage guidance like the reference
        method="sample", rngs={"diffusion": jax.random.key(5)})
    assert out.shape == (2, 16, 16, 3)
    assert 0.0 <= float(out.min()) and float(out.max()) <= 1.0

    # every stage's output on request, resolutions ascending
    outs = model.apply(
        variables, text_embeds=emb, text_masks=mask,
        return_all_unet_outputs=True,
        method="sample", rngs={"diffusion": jax.random.key(5)})
    assert [o.shape for o in outs] == [(2, 8, 8, 3), (2, 16, 16, 3)]
    # stage-1 output of sample() == a direct sample_stage call with
    # the same rng stream (the cascade really starts from stage 1)
    direct = model.apply(
        variables, 1, (2, 8, 8, 3), emb, mask,
        method="sample_stage", rngs={"diffusion": jax.random.key(5)})
    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(direct),
                               atol=1e-6)

    # default text mask derivation + truncation
    trunc = model.apply(
        variables, text_embeds=emb, stop_at_unet_number=1,
        method="sample", rngs={"diffusion": jax.random.key(6)})
    assert trunc.shape == (2, 8, 8, 3)

    with pytest.raises(ValueError, match="text"):
        model.apply(variables, method="sample",
                    rngs={"diffusion": jax.random.key(7)})


def test_sample_skip_steps():
    """skip_steps drops the noisiest timestep pairs (reference
    p_sample_loop timesteps[skip_steps:]): fewer denoise iterations,
    same shapes; skipping everything but one step still returns a
    valid [0, 1] image."""
    model = tiny_imagen()
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (2, 3, 16, 16)),
        jnp.float32)
    emb = jnp.asarray(np.random.default_rng(1).normal(size=(2, 6, 32)),
                      jnp.float32)
    mask = jnp.ones((2, 6), jnp.int32)
    variables = model.init(
        {"params": jax.random.key(0), "diffusion": jax.random.key(1)},
        images, emb, mask)
    n_steps = model.config.timesteps if isinstance(
        model.config.timesteps, int) else model.config.timesteps[0]
    full = model.apply(
        variables, 1, (2, 16, 16, 3), emb, mask,
        method="sample_stage", rngs={"diffusion": jax.random.key(2)})
    skipped = model.apply(
        variables, 1, (2, 16, 16, 3), emb, mask,
        skip_steps=n_steps - 1,
        method="sample_stage", rngs={"diffusion": jax.random.key(2)})
    assert skipped.shape == full.shape == (2, 16, 16, 3)
    for out in (full, skipped):
        assert 0.0 <= float(out.min()) and float(out.max()) <= 1.0
    assert not np.array_equal(np.asarray(full), np.asarray(skipped))


def test_imagen_trains_fsdp_sharded(tmp_path):
    """ZeRO-3 over the U-Net (VERDICT r4 #7): with sharding_degree=4
    stage 3, the wide conv/dense params must actually SHARD over the
    fsdp mesh axis (not replicate), and training must still step.
    The annotations live in models/imagen/unet.py (_conv/_attn_dense/
    _ff/_cond_dense -> logical "embed"/"mlp"/"heads" axes)."""
    from paddlefleetx_tpu.core import Engine
    from paddlefleetx_tpu.data import build_dataloader
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    filelist = _write_imagen_corpus(tmp_path, n=16)
    cfg = AttrDict({
        "Global": AttrDict({"device": "cpu", "seed": 2022,
                            "global_batch_size": None,
                            "local_batch_size": 2,
                            "micro_batch_size": 2}),
        "Engine": AttrDict({
            "max_steps": 2, "logging_freq": 1, "eval_freq": 1000,
            "mix_precision": AttrDict({}),
            "save_load": AttrDict({"save_steps": 1000,
                                   "output_dir": str(tmp_path / "o")}),
        }),
        "Model": AttrDict({
            "module": "ImagenModule",
            "name": "imagen_397M_text2im_64",
            "unet_number": 1,
            "image_sizes": (16,),
            "text_embed_dim": 32,
            "timesteps": 8,
            "unet_overrides": tuple(TINY_UNET.items()),
        }),
        "Loss": AttrDict({"name": "mse_loss", "p2_loss_weight_k": 1}),
        "Distributed": AttrDict({
            "dp_degree": 2, "mp_degree": 1, "pp_degree": 1,
            "sharding": AttrDict({"sharding_degree": 4,
                                  "sharding_stage": 3})}),
        "Optimizer": AttrDict({
            "name": "Adam",
            "lr": AttrDict({"name": "CosineAnnealingWithWarmupDecay",
                            "decay_steps": 100, "warmup_rate": 0.1,
                            "max_lr": 1e-3, "min_lr": 1e-4}),
            "grad_clip": AttrDict({"clip_norm": 1.0}),
        }),
        "Data": AttrDict({"Train": AttrDict({
            "dataset": AttrDict({
                "name": "ImagenDataset", "input_path": filelist,
                "input_resolution": 16, "max_seq_len": 8}),
            "sampler": AttrDict({"name": "DistributedBatchSampler",
                                 "batch_size": 2, "shuffle": False,
                                 "drop_last": True}),
            "loader": AttrDict({"collate_fn": "imagen_collate_fn",
                                "num_workers": 1}),
        })}),
    })
    process_configs(cfg, nranks=8)
    module = build_module(cfg)
    engine = Engine(cfg, module, mode="train")
    assert dict(engine.mesh.shape)["fsdp"] == 4

    # the wide params are REALLY sharded: some 4-D conv kernel and
    # some dense kernel must carry fsdp in their sharding spec, and
    # their per-device shard must be smaller than the global shape
    leaves = jax.tree.leaves(engine.state["params"])
    fsdp_sharded = [
        x for x in leaves
        if hasattr(x, "sharding") and "fsdp" in str(x.sharding.spec)]
    assert fsdp_sharded, "no param sharded over fsdp"
    conv_kernels = [x for x in fsdp_sharded if x.ndim == 4]
    assert conv_kernels, "no conv kernel sharded over fsdp"
    x = conv_kernels[0]
    shard_shape = x.sharding.shard_shape(x.shape)
    assert np.prod(shard_shape) < np.prod(x.shape)

    loader = build_dataloader(cfg.Data, "Train", num_replicas=1, rank=0)
    loader.batch_sampler.batch_size = cfg.Global.global_batch_size
    losses = []
    orig = module.training_step_end

    def capture(log):
        losses.append(log["loss"])
        orig(log)

    module.training_step_end = capture
    engine.fit(epoch=1, train_data_loader=loader)
    assert losses and all(np.isfinite(x) for x in losses)
