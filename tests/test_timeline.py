"""Unit pins for the per-thread timeline recorder (PR 18).

Covers the ring-buffer mechanics (bounded memory, wraparound,
mid-flight enable semantics), the disabled-mode cost discipline
(same <1%-of-a-step contract the metrics registry holds), and the
two derived views — per-track utilization and the fleet
``overlap_ratio`` whose lockstep-vs-async calibration
(`1/N` vs -> `1.0`) `tests/test_fleet.py` exercises end to end.
"""

import timeit

import pytest

from paddlefleetx_tpu.observability import timeline
from paddlefleetx_tpu.observability.timeline import (
    ThreadTimeline, overlap_ratio, utilization)


def _fill(track, state, pairs, trace=None):
    for t0, t1 in pairs:
        track.add(state, t0, t1, trace=trace)


# -- ring mechanics ----------------------------------------------------


def test_ring_is_bounded_and_wraps_oldest_first():
    tl = ThreadTimeline(enabled=True, cap=4)
    tr = tl.track("w")
    for i in range(10):
        tr.add(f"s{i}", 1.0 + i, 2.0 + i)
    ivs = tr.intervals()
    assert len(ivs) == 4                       # bounded at cap
    assert [iv[0] for iv in ivs] == ["s6", "s7", "s8", "s9"]
    # and the ring keeps rolling: one more append drops s6
    tr.add("s10", 20.0, 21.0)
    assert [iv[0] for iv in tr.intervals()][0] == "s7"


def test_track_registration_is_idempotent():
    tl = ThreadTimeline(enabled=True, cap=8)
    a = tl.track("worker")
    b = tl.track("worker")
    assert a is b                   # a restarted thread reattaches
    a.add("tick", 1.0, 2.0)
    assert len(b.intervals()) == 1


def test_interval_carries_state_times_and_trace():
    tl = ThreadTimeline(enabled=True, cap=8)
    tr = tl.track("w")
    tr.add("handoff_host", 5.0, 6.5, trace="abcd" * 4)
    state, t0, t1, trace = tr.intervals()[0]
    assert (state, t0, t1, trace) == ("handoff_host", 5.0, 6.5,
                                      "abcd" * 4)
    # t1 defaults to "now" for the begin()/add() pair idiom
    t0 = tr.begin()
    tr.add("tick", t0)
    _, s, e, _ = tr.intervals()[-1]
    assert e >= s > 0


def test_snapshot_since_scopes_and_keeps_empty_tracks():
    tl = ThreadTimeline(enabled=True, cap=8)
    tl.track("old").add("tick", 1.0, 2.0)
    tl.track("new").add("tick", 10.0, 11.0)
    tl.track("registered-but-idle")
    snap = tl.snapshot(since=5.0)
    assert snap["old"] == []               # ended before the window
    assert len(snap["new"]) == 1
    # an instrumented-but-idle thread still earns its Perfetto row
    assert snap["registered-but-idle"] == []


# -- enable/disable discipline -----------------------------------------


def test_disabled_records_nothing_and_begin_is_zero():
    tl = ThreadTimeline(enabled=False, cap=8)
    tr = tl.track("w")
    assert tr.begin() == 0.0
    tr.add("tick", tr.begin())
    tr.add("tick", 123.0, 124.0)           # even explicit stamps drop
    assert tr.intervals() == []


def test_mid_interval_enable_never_fabricates_interval():
    tl = ThreadTimeline(enabled=False, cap=8)
    tr = tl.track("w")
    t0 = tr.begin()                        # 0.0: recorder was off
    tl.set_enabled(True)
    tr.add("tick", t0)                     # must NOT become an
    assert tr.intervals() == []            # epoch-long interval
    t0 = tr.begin()                        # begun while on: recorded
    tr.add("tick", t0)
    assert len(tr.intervals()) == 1
    tl.set_enabled(False)
    tr.add("tick", tr.begin())
    assert len(tr.intervals()) == 1        # off again: dropped


def test_disabled_overhead_under_one_percent_of_step():
    """Same cost contract as the disabled metrics registry: the
    begin/add pair on a hot loop must stay far below 1% of the
    fastest steady-state step this suite observes (~10 ms)."""
    was = timeline.enabled()     # earlier in-process bench/fleet runs
    timeline.set_enabled(False)  # may have left the recorder on
    tr = timeline.track("tt-overhead-probe")
    n = 10_000

    def begin_add():
        tr.add("tick", tr.begin())

    try:
        # best-of-5 to dodge scheduler jitter on shared CI hosts
        per_call = min(
            timeit.timeit(begin_add, number=n) for _ in range(5)) / n
    finally:
        timeline.set_enabled(was)
    step_budget_s = 0.010
    assert per_call < 0.01 * step_budget_s, per_call
    assert tr.intervals() == []


# -- derived views -----------------------------------------------------


def test_utilization_splits_busy_from_wait_states():
    tl = ThreadTimeline(enabled=True, cap=16)
    w = tl.track("fleet-worker-0")
    _fill(w, "tick", [(10.0, 13.0)])
    _fill(w, "idle", [(13.0, 14.0)])
    _fill(w, "park", [(14.0, 16.0)])
    u = utilization(tl.snapshot())["fleet-worker-0"]
    assert u["busy_s"] == pytest.approx(3.0)
    assert u["wait_s"] == pytest.approx(3.0)
    assert u["util"] == pytest.approx(0.5)
    assert u["window_s"] == pytest.approx(6.0)
    # every documented wait state counts as wait, nothing else does
    assert timeline.WAIT_STATES == {
        "idle", "wait", "park", "poll", "harvest_wait"}


def test_utilization_empty_track_is_zero_not_nan():
    tl = ThreadTimeline(enabled=True, cap=4)
    tl.track("quiet")
    u = utilization(tl.snapshot())["quiet"]
    assert u["util"] == 0.0 and u["window_s"] == 0.0


def test_overlap_ratio_lockstep_floor_is_one_over_n():
    tl = ThreadTimeline(enabled=True, cap=16)
    # back-to-back ticks, never concurrent: the lockstep shape
    _fill(tl.track("fleet-worker-0"), "tick", [(10.0, 11.0), (12.0, 13.0)])
    _fill(tl.track("fleet-worker-1"), "tick", [(11.0, 12.0), (13.0, 14.0)])
    assert overlap_ratio(tl.snapshot()) == pytest.approx(1 / 2)


def test_overlap_ratio_full_overlap_is_one():
    tl = ThreadTimeline(enabled=True, cap=16)
    for i in range(3):
        _fill(tl.track(f"fleet-worker-{i}"), "tick", [(10.0, 12.0)])
    assert overlap_ratio(tl.snapshot()) == pytest.approx(1.0)


def test_overlap_ratio_partial_overlap_lands_between():
    tl = ThreadTimeline(enabled=True, cap=16)
    _fill(tl.track("fleet-worker-0"), "tick", [(10.0, 11.0)])
    _fill(tl.track("fleet-worker-1"), "tick", [(10.5, 11.5)])
    # depth 1 over half the busy window, depth 2 over the other
    # half: mean depth 4/3 over 2 tracks
    assert overlap_ratio(tl.snapshot()) == pytest.approx(2 / 3)


def test_overlap_ratio_ignores_other_tracks_and_states():
    tl = ThreadTimeline(enabled=True, cap=16)
    _fill(tl.track("fleet-worker-0"), "tick", [(10.0, 11.0)])
    _fill(tl.track("fleet-worker-0"), "idle", [(11.0, 19.0)])
    _fill(tl.track("fleet-worker-1"), "park", [(10.0, 19.0)])
    _fill(tl.track("kv-spill-writer"), "tick", [(10.0, 19.0)])
    # only worker TICKS count: one contributing track => ratio 1.0
    assert overlap_ratio(tl.snapshot()) == pytest.approx(1.0)


def test_overlap_ratio_none_without_data():
    tl = ThreadTimeline(enabled=True, cap=4)
    assert overlap_ratio(tl.snapshot()) is None
    tl.track("fleet-worker-0").add("tick", 5.0, 5.0)   # zero-width
    assert overlap_ratio(tl.snapshot()) is None
