"""End-to-end engine tests on the 8-device CPU mesh: loss decreases,
checkpoints round-trip, resume fast-forwards — the reference's TIPC
smoke semantics (SURVEY §4) as proper unit tests."""

import numpy as np
import pytest

from paddlefleetx_tpu.core import Engine
from paddlefleetx_tpu.data import build_dataloader
from paddlefleetx_tpu.models import build_module
from paddlefleetx_tpu.utils.config import AttrDict, process_configs

from test_data import make_corpus


def tiny_config(tmp_path, **overrides):
    cfg = AttrDict({
        "Global": AttrDict({
            "device": "cpu", "seed": 1024,
            "global_batch_size": None, "local_batch_size": 8,
            "micro_batch_size": 4,
        }),
        "Engine": AttrDict({
            "max_steps": 10, "logging_freq": 5, "eval_freq": 100,
            "eval_iters": 2,
            "mix_precision": AttrDict({"use_pure_fp16": False}),
            "save_load": AttrDict({"save_steps": 100,
                                   "output_dir": str(tmp_path / "out")}),
        }),
        "Model": AttrDict({
            "module": "GPTModule", "name": "GPT",
            "vocab_size": 128, "hidden_size": 32, "num_layers": 2,
            "num_attention_heads": 4, "ffn_hidden_size": 64,
            "max_position_embeddings": 64,
            "hidden_dropout_prob": 0.0,
            "attention_probs_dropout_prob": 0.0,
        }),
        "Distributed": AttrDict({
            "dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
            "sharding": AttrDict({"sharding_degree": 2,
                                  "sharding_stage": 1}),
        }),
        "Optimizer": AttrDict({
            "name": "FusedAdamW", "weight_decay": 0.01,
            "beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
            "lr": AttrDict({"name": "CosineAnnealingWithWarmupDecay",
                            "decay_steps": 100, "warmup_rate": 0.1,
                            "max_lr": 1e-2, "min_lr": 1e-3}),
            "grad_clip": AttrDict({"name": "ClipGradByGlobalNorm",
                                   "clip_norm": 1.0}),
        }),
        "Data": AttrDict({"Train": AttrDict({
            "dataset": AttrDict({
                "name": "GPTDataset", "input_dir": str(tmp_path),
                "split": [1, 0, 0], "max_seq_len": 32,
                "num_samples": 400, "mode": "Train", "eos_id": 127,
                "build_data_file": True}),
            "sampler": AttrDict({"name": "GPTBatchSampler",
                                 "batch_size": 8, "shuffle": False,
                                 "drop_last": True}),
            "loader": AttrDict({"collate_fn": "gpt_collate_fn"}),
        })}),
    })
    for path, value in overrides.items():
        node = cfg
        keys = path.split(".")
        for k in keys[:-1]:
            node = node[k]
        node[keys[-1]] = value
    return process_configs(cfg, nranks=8)


def _build(tmp_path, **overrides):
    make_corpus(tmp_path, n_docs=40, doc_len_range=(20, 60), vocab=128,
                eos=127)
    cfg = tiny_config(tmp_path, **overrides)
    module = build_module(cfg)
    engine = Engine(cfg, module, mode="train")
    # global batch: sampler covers all 8 dataflow slots from one process
    loader = build_dataloader(cfg.Data, "Train", num_replicas=1, rank=0)
    # sampler batch = per-process batch = global batch (single process)
    loader.batch_sampler.batch_size = cfg.Global.global_batch_size
    return cfg, engine, loader


def test_fit_loss_decreases(tmp_path):
    cfg, engine, loader = _build(tmp_path)
    losses = []

    orig = engine.module.training_step_end

    def capture(log):
        losses.append(log["loss"])
        orig(log)

    engine.module.training_step_end = capture
    engine.fit(epoch=1, train_data_loader=loader)
    assert len(losses) == 2  # 10 steps, logging_freq 5
    assert losses[-1] < np.log(128)  # below uniform-random loss


def test_grad_accumulation_matches_single_batch(tmp_path):
    """acc=2 over the same global batch == acc=1 numerics."""
    cfg1, e1, loader1 = _build(tmp_path, **{"Engine.max_steps": 1})
    batch = next(iter(loader1))
    s1, m1 = e1._run_one(batch) if hasattr(e1, "_run_one") else (None, None)
    # run manually through both engines on the identical batch
    import flax.linen as nn
    with e1.mesh, nn.logical_axis_rules(e1.rules):
        _, metrics1 = e1._train_step(e1.state, e1._put_batch(batch))

    cfg2, e2, _ = _build(tmp_path, **{
        "Engine.max_steps": 1, "Global.micro_batch_size": 2})
    assert e2.accumulate_steps == 4
    with e2.mesh, nn.logical_axis_rules(e2.rules):
        _, metrics2 = e2._train_step(e2.state, e2._put_batch(batch))
    np.testing.assert_allclose(float(metrics1["loss"]),
                               float(metrics2["loss"]), rtol=1e-5)


def test_checkpoint_save_load_resume(tmp_path):
    cfg, engine, loader = _build(tmp_path, **{"Engine.max_steps": 3})
    engine.fit(epoch=1, train_data_loader=loader)
    engine.save(epoch=1)
    step = int(engine.state["step"])
    params_before = jax.tree.map(np.asarray, engine.state["params"])

    cfg2, engine2, _ = _build(
        tmp_path, **{"Engine.max_steps": 3,
                     "Engine.save_load.ckpt_dir": str(tmp_path / "out")})
    assert int(engine2.state["step"]) == step
    assert engine2._load_recovery["consumed_samples"] == \
        step * cfg.Global.global_batch_size
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params_before, engine2.state["params"])


@pytest.mark.parametrize("train_scan,restore_scan", [(True, False),
                                                     (False, True)])
def test_checkpoint_restores_across_scan_layers_toggle(
        tmp_path, train_scan, restore_scan):
    """scan_layers is a performance knob, not a checkpoint format: a
    checkpoint trained with the nn.scan-stacked decoder restores into
    an unrolled model and vice versa — params AND optimizer moments
    converted between the stacked and per-layer layouts."""
    cfg, engine, loader = _build(
        tmp_path, **{"Engine.max_steps": 2,
                     "Model.scan_layers": train_scan})
    engine.fit(epoch=1, train_data_loader=loader)
    engine.save(epoch=1)
    step = int(engine.state["step"])
    params_trained = jax.tree.map(np.asarray, engine.state["params"])

    cfg2, engine2, _ = _build(
        tmp_path, **{"Engine.max_steps": 2,
                     "Model.scan_layers": restore_scan,
                     "Engine.save_load.ckpt_dir": str(tmp_path / "out")})
    assert int(engine2.state["step"]) == step
    gpt = engine2.state["params"]["gpt"]
    if restore_scan:
        assert "decoder" in gpt and "decoder_0" not in gpt
        stacked = gpt["decoder"]
        jax.tree.map(
            lambda full, sliced: np.testing.assert_array_equal(
                np.asarray(full[0]), np.asarray(sliced)),
            dict(stacked),
            dict(params_trained["gpt"]["decoder_0"]))
    else:
        assert "decoder_0" in gpt and "decoder" not in gpt
        jax.tree.map(
            lambda sliced, full: np.testing.assert_array_equal(
                np.asarray(sliced), np.asarray(full[0])),
            dict(gpt["decoder_0"]),
            dict(params_trained["gpt"]["decoder"]))
    # the converted state must step normally
    import flax.linen as nn
    batch = next(iter(loader))
    with engine2.mesh, nn.logical_axis_rules(engine2.rules):
        _, metrics = engine2._train_step(engine2.state,
                                         engine2._put_batch(batch))
    assert np.isfinite(float(metrics["loss"]))


def test_sigterm_preemption_saves_and_stops(tmp_path):
    """TPU preemption semantics: SIGTERM mid-run checkpoints at the
    next step boundary and fit returns cleanly (no periodic-save tail
    lost), with the previous handler restored afterwards."""
    import os
    import signal as _signal

    cfg, engine, loader = _build(tmp_path, **{"Engine.max_steps": 50})

    def kicking(loader, after):
        for i, b in enumerate(loader):
            yield b
            if i == after - 1:
                os.kill(os.getpid(), _signal.SIGTERM)

    prev = _signal.getsignal(_signal.SIGTERM)
    # the input prefetcher pulls prefetch_depth batches ahead of the
    # trained step, so kick that many pulls later to land the signal
    # after >= 2 TRAINED steps (the pull count is not the step count)
    engine.fit(epoch=1, train_data_loader=kicking(
        loader, 2 + engine.prefetch_depth))
    assert _signal.getsignal(_signal.SIGTERM) is prev

    step = int(engine.state["step"])
    assert 2 <= step < 50, step
    from paddlefleetx_tpu.core import checkpoint as ckpt
    path = ckpt.latest_checkpoint(str(tmp_path / "out"))
    assert path is not None and path.endswith(f"step_{step}")

    # and a restarted engine resumes from the preemption point
    cfg2, engine2, _ = _build(
        tmp_path, **{"Engine.max_steps": 50,
                     "Engine.save_load.ckpt_dir": str(tmp_path / "out")})
    assert int(engine2.state["step"]) == step


def test_sigterm_during_eval_breaks_out_and_saves(tmp_path):
    """A SIGTERM landing mid-eval must not wait for the whole eval
    pass (preemption grace windows are short): the eval loop breaks,
    and the preemption checkpoint is still written."""
    import os
    import signal as _signal

    cfg, engine, loader = _build(
        tmp_path, **{"Engine.max_steps": 4,
                     "Engine.run_mode": "step",
                     "Engine.eval_freq": 2,
                     "Engine.eval_iters": 100})
    eval_batches = []

    def eval_loader():
        for i, b in enumerate(loader):
            if i == 1:   # signal arrives while eval is running
                os.kill(os.getpid(), _signal.SIGTERM)
            eval_batches.append(i)
            yield b

    prev = _signal.getsignal(_signal.SIGTERM)
    engine.fit(epoch=1, train_data_loader=loader,
               valid_data_loader=eval_loader())
    assert _signal.getsignal(_signal.SIGTERM) is prev
    # eval stopped long before its 100-iteration budget
    assert len(eval_batches) <= 3, eval_batches
    from paddlefleetx_tpu.core import checkpoint as ckpt
    step = int(engine.state["step"])
    path = ckpt.latest_checkpoint(str(tmp_path / "out"))
    assert path is not None and path.endswith(f"step_{step}")


def test_preemption_handler_opt_out(tmp_path):
    """save_on_preemption: False leaves SIGTERM handling alone."""
    import signal as _signal

    cfg, engine, loader = _build(
        tmp_path, **{"Engine.max_steps": 2,
                     "Engine.save_load.save_on_preemption": False})
    seen = []

    def mine(*a):
        seen.append(a)

    prev = _signal.signal(_signal.SIGTERM, mine)
    try:
        engine.fit(epoch=1, train_data_loader=loader)
        # identity: OUR handler stayed installed the whole time (an
        # engine lambda would also be callable — compare the object)
        assert _signal.getsignal(_signal.SIGTERM) is mine
    finally:
        _signal.signal(_signal.SIGTERM, prev)


def test_async_checkpoint_save_then_resume(tmp_path):
    """Engine.save_load.async_save overlaps the TensorStore write with
    training; a fresh engine must restore the identical state (the
    load path waits for any in-flight save)."""
    cfg, engine, loader = _build(
        tmp_path, **{"Engine.max_steps": 2,
                     "Engine.save_load.async_save": True})
    assert engine.async_save
    engine.fit(epoch=1, train_data_loader=loader)
    engine.save(epoch=1)
    step = int(engine.state["step"])
    params_before = jax.tree.map(np.asarray, engine.state["params"])

    cfg2, engine2, _ = _build(
        tmp_path, **{"Engine.max_steps": 2,
                     "Engine.save_load.ckpt_dir": str(tmp_path / "out")})
    assert int(engine2.state["step"]) == step
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params_before, engine2.state["params"])


def test_checkpoint_restores_across_mesh_and_scan_toggle(tmp_path):
    """The hardest combined case: save under the nn.scan layout on a
    dp2 x mp2 x fsdp2 mesh, restore into an UNROLLED model on a
    different mesh split — the layout adapter must not inherit the
    checkpoint's recorded shardings (Orbax calls that unsafe across
    topologies); it restores via explicit single-device placement and
    re-places onto the new mesh."""
    cfg, engine, loader = _build(tmp_path, **{"Engine.max_steps": 2})
    engine.fit(epoch=1, train_data_loader=loader)
    engine.save(epoch=1)
    step = int(engine.state["step"])
    stacked_before = jax.tree.map(
        np.asarray, engine.state["params"]["gpt"]["decoder"])

    cfg2, engine2, loader2 = _build(
        tmp_path, **{"Engine.max_steps": 4,
                     "Model.scan_layers": False,
                     "Distributed.dp_degree": 2,
                     "Distributed.mp_degree": 4,
                     "Distributed.sharding.sharding_degree": 1,
                     "Engine.save_load.ckpt_dir": str(tmp_path / "out")})
    assert dict(engine2.mesh.shape) != dict(engine.mesh.shape)
    assert int(engine2.state["step"]) == step
    gpt = engine2.state["params"]["gpt"]
    assert "decoder_0" in gpt
    jax.tree.map(
        lambda sliced, full: np.testing.assert_array_equal(
            np.asarray(sliced), np.asarray(full[1])),
        dict(gpt["decoder_1"]), dict(stacked_before))
    import flax.linen as nn
    batch = next(iter(loader2))
    with engine2.mesh, nn.logical_axis_rules(engine2.rules):
        _, metrics = engine2._train_step(engine2.state,
                                         engine2._put_batch(batch))
    assert np.isfinite(float(metrics["loss"]))


def test_checkpoint_restores_across_topologies(tmp_path):
    """Save on mesh A (dp2 x mp2 x sharding2), restore on mesh B
    (mp4 x pp... different axis split) — the SURVEY 'hard part' the
    reference dodges with per-rank dirs: its mp_XX_sharding_XX_pp_XX
    checkpoint layout cannot be reloaded on a different topology at
    all, while the Orbax layout here is keyed by parameter name only."""
    cfg, engine, loader = _build(tmp_path, **{"Engine.max_steps": 2})
    engine.fit(epoch=1, train_data_loader=loader)
    engine.save(epoch=1)
    step = int(engine.state["step"])
    params_before = jax.tree.map(np.asarray, engine.state["params"])

    cfg2, engine2, loader2 = _build(
        tmp_path, **{"Engine.max_steps": 4,
                     "Distributed.dp_degree": 2,
                     "Distributed.mp_degree": 4,
                     "Distributed.sharding.sharding_degree": 1,
                     "Engine.save_load.ckpt_dir": str(tmp_path / "out")})
    assert dict(engine2.mesh.shape) != dict(engine.mesh.shape)
    assert int(engine2.state["step"]) == step
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params_before, engine2.state["params"])
    # the restored state trains on the new mesh
    import flax.linen as nn
    batch = next(iter(loader2))
    with engine2.mesh, nn.logical_axis_rules(engine2.rules):
        state, metrics = engine2._train_step(engine2.state,
                                             engine2._put_batch(batch))
    assert np.isfinite(float(metrics["loss"]))
    assert int(state["step"]) == step + 1


import jax  # noqa: E402  (used in helpers above)


def test_epoch_run_mode_evaluates_at_epoch_end(tmp_path):
    """run_mode='epoch' (the vis configs): no mid-epoch eval even with
    eval_freq=1, one full-loader eval at epoch end, and eval_iters=-1
    walks the whole loader instead of breaking at batch 0 with a NaN
    mean (reference eager_engine.py:296-372 gates on run_mode)."""
    cfg, engine, loader = _build(tmp_path, **{
        "Engine.max_steps": 3, "Engine.eval_freq": 1,
        "Engine.eval_iters": -1, "Engine.run_mode": "epoch"})
    assert engine.eval_iters is None  # -1 -> walk the whole loader
    assert engine.test_iters > 0  # not eval_iters * 10 == -10

    step_logs, epoch_logs = [], []
    engine.module.validation_step_end = step_logs.append
    engine.module.validation_epoch_end = epoch_logs.append

    valid_batches = [next(iter(loader)) for _ in range(2)]
    engine.fit(epoch=1, train_data_loader=loader,
               valid_data_loader=valid_batches)
    assert len(epoch_logs) == 1  # once, at epoch end — not per step
    assert len(step_logs) == len(valid_batches)  # whole loader walked
    assert np.isfinite(epoch_logs[0]["loss"])


def test_profiler_window_writes_trace(tmp_path):
    """Profiler.enable traces steps [start, stop) into profiler_log
    (reference eager_engine.py:202-224 window semantics)."""
    import os
    cfg, engine, loader = _build(tmp_path, **{"Engine.max_steps": 6})
    prof_dir = str(tmp_path / "prof")
    engine._prof_window = (2, 4)
    engine._prof_dir = prof_dir
    engine._prof_active = False
    engine.fit(epoch=1, train_data_loader=loader)
    found = []
    for root, _dirs, files in os.walk(prof_dir):
        found.extend(files)
    assert any(f.endswith(".xplane.pb") or "trace" in f for f in found), \
        found


def test_predict_walks_test_loader(tmp_path):
    """Engine.predict runs module.predict_step per batch and fires
    test_step_end (reference eager_engine.py:531-583)."""
    cfg, engine, loader = _build(tmp_path, **{"Engine.test_iters": 3})
    logs = []
    engine.module.test_step_end = lambda log: logs.append(log)
    outs = engine.predict(epoch=1, test_data_loader=loader)
    assert len(outs) == 3 == len(logs)           # capped at test_iters
    assert all(np.isfinite(log["loss"]) for log in logs)
    # default predict_step is eval-mode loss: near uniform-random CE
    assert abs(logs[0]["loss"] - np.log(128)) < 1.0


def test_predict_honors_module_override(tmp_path):
    """A module predict_step override (custom prediction output) is
    what Engine.predict jits and returns."""
    cfg, engine, loader = _build(tmp_path, **{"Engine.test_iters": 1})

    def predict_argmax(params, batch, rng):
        import jax.numpy as jnp
        tokens = batch[0]
        logits = engine.module.model.apply({"params": params}, tokens)
        return {"loss": jnp.zeros(()),
                "pred": jnp.argmax(logits, axis=-1)}

    engine.module.predict_step = predict_argmax
    engine._build_steps()          # re-jit with the override
    outs = engine.predict(epoch=1, test_data_loader=loader)
    assert len(outs) == 1 and "pred" in outs[0]
    # [global batch, seq]
    assert outs[0]["pred"].shape == (cfg.Global.global_batch_size, 32)


def test_sharding_offload_shardings_request_pinned_host():
    """offload_to_host places every non-scalar optimizer leaf in
    pinned host memory (reference sharding_offload semantics)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddlefleetx_tpu.parallel.sharding import (
        device_memory_kinds, offload_to_host,
    )
    kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
    if "pinned_host" not in kinds:
        pytest.skip("backend has no pinned_host memory space "
                    f"(addressable: {sorted(kinds)})")
    mesh = Mesh(np.array(jax.devices()), ("fsdp",))
    tree = {"mu": NamedSharding(mesh, P("fsdp")),
            "count": NamedSharding(mesh, P())}
    shapes = {"mu": jax.ShapeDtypeStruct((16,), jnp.float32),
              "count": jax.ShapeDtypeStruct((), jnp.int32)}
    host = offload_to_host(tree, shapes)
    assert host["mu"].memory_kind == "pinned_host"
    assert host["count"].memory_kind != "pinned_host"  # replicated stays
    # replicated non-scalars (indivisible moments) also stay on device:
    # the SPMD partitioner rejects replicated host placement
    repl = offload_to_host(
        {"v": NamedSharding(mesh, P())},
        {"v": jax.ShapeDtypeStruct((7,), jnp.float32)})
    assert repl["v"].memory_kind != "pinned_host"
    dev = device_memory_kinds(host)
    assert dev["mu"].memory_kind == "device"
    # pinned_host placement is real on this backend outside jit
    x = jax.device_put(jnp.ones(16), host["mu"])
    assert x.sharding.memory_kind == "pinned_host"


def test_sharding_offload_downgrades_on_cpu(tmp_path, monkeypatch):
    """On platforms without in-jit host offload the flag warns and
    training proceeds with device-resident optimizer state."""
    from paddlefleetx_tpu.utils.log import logger as pfx_logger
    warnings = []
    monkeypatch.setattr(
        pfx_logger, "warning",
        lambda msg, *a, **k: warnings.append(msg % a if a else msg))
    cfg, engine, loader = _build(
        tmp_path,
        **{"Distributed.sharding.sharding_offload": True,
           "Engine.max_steps": 2})
    assert engine._opt_offload is False           # gated, not crashed
    assert any("sharding_offload" in w for w in warnings)  # loudly
    engine.fit(epoch=1, train_data_loader=loader)
    assert int(engine.state["step"]) == 2


def test_profiler_summary_printed(tmp_path, monkeypatch):
    """With the profiler window configured, fit() ends with a host
    step-time summary (reference _print_summary parity)."""
    from paddlefleetx_tpu.utils.log import logger as pfx_logger
    lines = []
    monkeypatch.setattr(
        pfx_logger, "info",
        lambda msg, *a, **k: lines.append(msg % a if a else str(msg)))
    cfg, engine, loader = _build(tmp_path, **{"Engine.max_steps": 6,
                                              "Engine.logging_freq": 2})
    engine._prof_window = (2, 4)
    engine._prof_dir = str(tmp_path / "prof")
    engine._prof_active = False
    engine.fit(epoch=1, train_data_loader=loader)
    assert any("Profiler summary" in l for l in lines)
    assert any("steady state" in l for l in lines)
    assert any("tokens/s" in l for l in lines)


@pytest.mark.parametrize("knob", [False, True],
                         ids=["gspmd", "rings"])
def test_profiler_summary_mp_collective_line(tmp_path, monkeypatch,
                                             knob):
    """mp>1 summaries carry a measured mp-collective line naming the
    dispatched path (ISSUE 2: recorded alongside 'h2d input wait')."""
    from paddlefleetx_tpu.utils.log import logger as pfx_logger
    lines = []
    monkeypatch.setattr(
        pfx_logger, "info",
        lambda msg, *a, **k: lines.append(msg % a if a else str(msg)))
    overrides = {"Engine.max_steps": 2, "Engine.logging_freq": 1}
    if knob:
        overrides.update({"Model.sequence_parallel": True,
                          "Model.use_collective_matmul": True})
    cfg, engine, loader = _build(tmp_path, **overrides)
    engine._step_costs = [0.1, 0.1]
    engine._prof_dir = str(tmp_path / "prof")
    engine._print_summary()
    mp_lines = [l for l in lines if "mp collective" in l]
    assert mp_lines, lines
    want = "decomposed overlapped rings" if knob \
        else "plain GSPMD all-gather/reduce-scatter"
    assert want in mp_lines[0]


def test_grad_accum_carry_is_param_sharded(tmp_path):
    """ISSUE 2 satellite: the fp32 grad_sum carry of the accumulation
    scan is constrained to the param PartitionSpecs — the zero tree
    lands mp/fsdp-sharded, not replicated per chip."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    cfg, engine, loader = _build(tmp_path)
    assert engine.accumulate_steps > 1      # the scan path is active
    shardings = engine.state_shardings["params"]
    # the default mesh (mp2 x fsdp2, stage 1) leaves params replicated
    # over fsdp but mp-sharded — the accumulator must pick that up
    assert any(s.spec != P() for s in jax.tree.leaves(shardings))

    import flax.linen as nn
    with engine.mesh, nn.logical_axis_rules(engine.rules):
        zero = jax.jit(lambda p: jax.tree.map(
            lambda q, s: jax.lax.with_sharding_constraint(
                jnp.zeros(q.shape, jnp.float32), s),
            p, engine.state_shardings["params"]))(engine.state["params"])
    for z, s in zip(jax.tree.leaves(zero), jax.tree.leaves(shardings)):
        assert z.dtype == jnp.float32
        # spec equality is structural (P() vs P(None, None) differ);
        # equivalence is the semantic check
        assert z.sharding.is_equivalent_to(s, z.ndim)
    # and the real accumulating train step still runs under the
    # constraint (a spec/structure mismatch would fail at trace time)
    batch = next(iter(loader))
    with engine.mesh, nn.logical_axis_rules(engine.rules):
        state, metrics = engine._train_step(engine.state,
                                            engine._put_batch(batch))
    engine.state = state
    assert np.isfinite(float(metrics["loss"]))


# -- input prefetch -----------------------------------------------------

class _FakePrefetchHost:
    """Just enough engine surface for Engine._prefetch_iter: records
    the interleaving of device puts and yields."""

    def __init__(self, events, depth):
        self.events = events
        self.prefetch_depth = depth
        host = self

        class _Mod:
            @staticmethod
            def pretreating_batch(b):
                return b

        self.module = _Mod()

    def _put_batch(self, b):
        self.events.append(("put", b))
        return b


def test_prefetch_iter_stages_ahead_and_preserves_order():
    """The double-buffer contract: batch N+depth's device put is
    ISSUED before batch N is handed to the consumer (so the transfer
    overlaps step N's compute), loader order is preserved, and every
    yield carries a non-negative h2d wait sample."""
    events = []
    fake = _FakePrefetchHost(events, depth=2)
    got = []
    for batch, wait in Engine._prefetch_iter(fake, [0, 1, 2, 3]):
        events.append(("yield", batch))
        got.append(batch)
        assert wait >= 0.0
    assert got == [0, 1, 2, 3]
    assert [b for e, b in events if e == "put"] == [0, 1, 2, 3]
    assert events.index(("put", 2)) < events.index(("yield", 0))
    assert events.index(("put", 3)) < events.index(("yield", 1))


def test_prefetch_iter_depth_zero_is_synchronous():
    """depth<=0 degrades to the old synchronous per-step put — no
    batch is staged before the previous one is consumed."""
    events = []
    fake = _FakePrefetchHost(events, depth=0)
    for batch, _w in Engine._prefetch_iter(fake, [0, 1, 2]):
        events.append(("yield", batch))
    assert events == [("put", 0), ("yield", 0), ("put", 1),
                      ("yield", 1), ("put", 2), ("yield", 2)]


def test_prefetch_iter_short_loader_drains():
    """Loaders shorter than the prefetch depth still yield every
    batch exactly once."""
    events = []
    fake = _FakePrefetchHost(events, depth=4)
    got = [b for b, _w in Engine._prefetch_iter(fake, [0, 1])]
    assert got == [0, 1]


def test_fit_records_h2d_wait_per_step(tmp_path):
    """The step loop records one h2d wait sample per trained step
    (the _step_costs summary's input-stall line feeds off these)."""
    cfg, engine, loader = _build(tmp_path)
    assert engine.prefetch_depth == 2   # config default
    engine.fit(epoch=1, train_data_loader=loader)
    assert len(engine._h2d_waits) == cfg.Engine.max_steps
    assert all(w >= 0.0 for w in engine._h2d_waits)


def test_fit_with_prefetch_disabled_matches_defaults(tmp_path):
    """Engine.prefetch_depth=0 (sync path) trains to the same loss
    trajectory as the staged path — prefetch must not reorder or
    drop batches."""
    losses = {}
    for depth in (2, 0):
        cfg, engine, loader = _build(
            tmp_path, **{"Engine.prefetch_depth": depth})
        seen = []
        orig = engine.module.training_step_end

        def capture(log, seen=seen):
            seen.append(log["loss"])

        engine.module.training_step_end = capture
        engine.fit(epoch=1, train_data_loader=loader)
        losses[depth] = seen
    assert losses[2] == pytest.approx(losses[0], rel=1e-6)
