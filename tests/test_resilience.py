"""Resilience subsystem: crash-consistent checkpoints, fault
injection, the step watchdog, and kill -> resume determinism
(docs/robustness.md).

The checkpoint tests build real Orbax step dirs and then attack them
the way a crash would — delete the manifest (torn write), truncate a
payload file (at-rest corruption) — and assert the resolve/load path
refuses, falls back, and records ``ckpt_fallback``. The engine tests
drill the full save -> die -> restore loop in-process with
``PFX_FAULTS_MODE=raise`` (the subprocess version with a real SIGKILL
is scripts/chaos_smoke.py) and pin loss-identical resume.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from paddlefleetx_tpu.core import checkpoint as ckpt
from paddlefleetx_tpu.core.resilience import (
    FaultInjector, InjectedKill, StepWatchdog, dump_all_stacks,
)

from test_engine import _build


class Recorder:
    """Event-collecting stand-in for the flight recorder."""

    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        self.events.append({"event": event, **fields})

    def of(self, event):
        return [e for e in self.events if e["event"] == event]


def _fake_step_dir(root, epoch, step, commit=True, payload=b"x" * 64):
    """A step dir with one payload file, optionally committed."""
    path = os.path.join(root, f"epoch_{epoch}_step_{step}")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "state.bin"), "wb") as f:
        f.write(payload)
    if commit:
        ckpt.write_manifest(path, {"epoch": epoch, "step": step})
    return path


# -- manifest write/verify ----------------------------------------------


def test_manifest_roundtrip_and_verify(tmp_path):
    path = _fake_step_dir(str(tmp_path), 1, 2)
    mpath = os.path.join(path, ckpt.MANIFEST_NAME)
    payload = json.load(open(mpath))
    assert payload["format"] == 1
    assert payload["meta"]["step"] == 2
    assert payload["files"]["state.bin"] == 64
    assert "state.bin" in payload["sha256"]   # small file gets a hash
    # the manifest never lists itself or temp files
    assert ckpt.MANIFEST_NAME not in payload["files"]
    assert ckpt.verify_checkpoint(path) is None

    # truncation = size mismatch
    with open(os.path.join(path, "state.bin"), "ab") as f:
        f.truncate(63)
    assert "size mismatch" in ckpt.verify_checkpoint(path)

    # same-size bit flip = hash mismatch
    with open(os.path.join(path, "state.bin"), "wb") as f:
        f.write(b"y" * 63 + b"x")
    with open(os.path.join(path, "state.bin"), "ab") as f:
        f.truncate(64)
    assert "hash mismatch" in ckpt.verify_checkpoint(path)

    os.remove(os.path.join(path, "state.bin"))
    assert "missing file" in ckpt.verify_checkpoint(path)

    os.remove(mpath)
    assert "no committed manifest" in ckpt.verify_checkpoint(path)


def test_large_files_are_size_checked_not_hashed(tmp_path):
    big = b"z" * (ckpt._HASH_MAX_BYTES + 1)
    path = _fake_step_dir(str(tmp_path), 1, 1, payload=big)
    payload = json.load(open(os.path.join(path, ckpt.MANIFEST_NAME)))
    assert "state.bin" not in payload["sha256"]
    assert payload["files"]["state.bin"] == len(big)
    assert ckpt.verify_checkpoint(path) is None


# -- latest_checkpoint resolution ---------------------------------------


def test_latest_checkpoint_skips_uncommitted_dir(tmp_path):
    """The satellite pin: a dir matching the name regex but left by a
    mid-write kill (no manifest) must NOT be selected."""
    rec = Recorder()
    old = _fake_step_dir(str(tmp_path), 1, 2, commit=True)
    _fake_step_dir(str(tmp_path), 1, 4, commit=False)   # torn write
    assert ckpt.latest_checkpoint(str(tmp_path), recorder=rec) == old
    (ev,) = rec.of("ckpt_fallback")
    assert ev["stage"] == "resolve" and ev["to"] == old
    assert "step_4" in ev["skipped"][0]["path"]
    assert "manifest" in ev["skipped"][0]["reason"]


def test_latest_checkpoint_none_when_nothing_verified(tmp_path):
    rec = Recorder()
    _fake_step_dir(str(tmp_path), 1, 4, commit=False)
    assert ckpt.latest_checkpoint(str(tmp_path), recorder=rec) is None
    (ev,) = rec.of("ckpt_fallback")
    assert ev["to"] is None and ev["stage"] == "resolve"


def test_latest_checkpoint_explicit_step_dir_passthrough(tmp_path):
    path = _fake_step_dir(str(tmp_path), 1, 4, commit=False)
    # an explicitly named step dir is returned as-is: load_checkpoint
    # owns verification (and raising) for explicit targets
    assert ckpt.latest_checkpoint(path) == path


# -- keep-last-k GC -----------------------------------------------------


def test_gc_keeps_k_newest_verified_and_spares_uncommitted(tmp_path):
    rec = Recorder()
    root = str(tmp_path)
    p2 = _fake_step_dir(root, 1, 2)
    p4 = _fake_step_dir(root, 1, 4)
    p6 = _fake_step_dir(root, 1, 6)
    torn = _fake_step_dir(root, 1, 8, commit=False)   # in-flight/torn
    deleted = ckpt.gc_checkpoints(root, keep_last_k=2, recorder=rec)
    assert deleted == [p2]
    assert not os.path.exists(p2)
    assert os.path.isdir(p4) and os.path.isdir(p6)
    assert os.path.isdir(torn)   # never a GC candidate
    (ev,) = rec.of("ckpt_gc")
    assert ev["keep_last_k"] == 2 and ev["kept"] == [p6, p4]


def test_gc_disabled_and_missing_dir(tmp_path):
    p2 = _fake_step_dir(str(tmp_path), 1, 2)
    assert ckpt.gc_checkpoints(str(tmp_path), keep_last_k=0) == []
    assert ckpt.gc_checkpoints(str(tmp_path), keep_last_k=-1) == []
    assert os.path.isdir(p2)
    assert ckpt.gc_checkpoints(str(tmp_path / "nope"), 1) == []


# -- fault injector -----------------------------------------------------


def test_fault_spec_parsing_and_validation():
    inj = FaultInjector(
        "kill@step=7,hang@tick=p0.5:2s,corrupt_ckpt@save=2,"
        "admit_fail@req=3", kill_mode="raise")
    kinds = [(f.kind, f.site) for f in inj._faults]
    assert kinds == [("kill", "step"), ("hang", "tick"),
                     ("corrupt_ckpt", "save"), ("admit_fail", "req")]
    assert inj._faults[1].prob == 0.5
    assert inj._faults[1].duration == 2.0
    assert inj._faults[0].at == 7
    for bad in ("kill@step", "nuke@step=1", "kill@lunch=1", "kill",
                "kill@step=x"):
        with pytest.raises(ValueError):
            FaultInjector(bad)
    with pytest.raises(ValueError, match="PFX_FAULTS_MODE"):
        FaultInjector("kill@step=1", kill_mode="maybe")


def test_fault_fire_is_one_shot_and_recorded():
    rec = Recorder()
    inj = FaultInjector("admit_fail@req=3", recorder=rec,
                        kill_mode="raise")
    assert inj.fire("req", 1) is None
    assert inj.fire("step", 3) is None      # wrong site
    assert inj.fire("req", 3) == "admit_fail"
    assert inj.fire("req", 3) is None       # one-shot
    (ev,) = rec.of("fault_injected")
    assert ev["kind"] == "admit_fail" and ev["count"] == 3


def test_fault_kill_raise_mode_emits_before_raising():
    rec = Recorder()
    inj = FaultInjector("kill@step=2", recorder=rec, kill_mode="raise")
    with pytest.raises(InjectedKill):
        inj.fire("step", 2)
    assert rec.of("fault_injected")   # durable before the act


def test_fault_probabilistic_is_seed_deterministic():
    fires = []
    for _ in range(2):
        inj = FaultInjector("admit_fail@req=p0.3", seed=7,
                            kill_mode="raise")
        fires.append([inj.fire("req", i) for i in range(1, 20)])
    assert fires[0] == fires[1]
    assert "admit_fail" in fires[0]


def test_fault_corrupt_ckpt_truncates_largest_file(tmp_path):
    path = _fake_step_dir(str(tmp_path), 1, 2)
    inj = FaultInjector("corrupt_ckpt@save=1", kill_mode="raise")
    assert inj.fire("save", 1, path=path) == "corrupt_ckpt"
    assert "size mismatch" in ckpt.verify_checkpoint(path)


def test_fault_injector_from_env(monkeypatch):
    monkeypatch.delenv("PFX_FAULTS", raising=False)
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("PFX_FAULTS", "  ")
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("PFX_FAULTS", "kill@step=9")
    monkeypatch.setenv("PFX_FAULTS_MODE", "raise")
    inj = FaultInjector.from_env()
    assert inj is not None and inj.kill_mode == "raise"


# -- step watchdog ------------------------------------------------------


def _wait_for(predicate, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def test_watchdog_detects_stall_once_per_phase():
    rec = Recorder()
    dog = StepWatchdog(name="t", factor=2.0, min_interval_s=0.05,
                       action="log", recorder=rec)
    dog.start()
    try:
        dog.arm(tag="step 1")
        assert _wait_for(lambda: dog.stalls == 1)
        time.sleep(0.2)            # still armed: must not re-fire
        assert dog.stalls == 1
        dog.disarm()
        (ev,) = rec.of("watchdog_stall")
        assert ev["name"] == "t" and ev["tag"] == "step 1"
        assert ev["waited_s"] > ev["deadline_s"]
        assert "watchdog" in ev["stacks"]   # the monitor's own frame
    finally:
        dog.stop()


def test_watchdog_adaptive_deadline_and_disarm_feeds_history():
    dog = StepWatchdog(name="t", factor=4.0, min_interval_s=0.01,
                       action="log")
    assert dog.deadline_s() == 0.01     # floor before any history
    for d in (0.5, 1.0, 1.5):
        dog._durations.append(d)
    assert dog.deadline_s() == pytest.approx(4.0)   # 4 x median 1.0
    dog.arm()
    dog.disarm()
    assert len(dog._durations) == 4     # completed phase recorded


def test_watchdog_abort_action_calls_abort_fn():
    aborted = threading.Event()
    dog = StepWatchdog(name="t", factor=2.0, min_interval_s=0.05,
                       action="abort")
    dog._abort_fn = aborted.set         # never os._exit in a test
    dog.start()
    try:
        dog.arm()
        assert _wait_for(aborted.is_set)
    finally:
        dog.disarm()
        dog.stop()
    with pytest.raises(ValueError, match="PFX_WATCHDOG_ACTION"):
        StepWatchdog(action="sometimes")


def test_watchdog_from_env(monkeypatch):
    monkeypatch.delenv("PFX_WATCHDOG", raising=False)
    assert StepWatchdog.from_env() is None
    monkeypatch.setenv("PFX_WATCHDOG", "1")
    monkeypatch.setenv("PFX_WATCHDOG_MIN_S", "30")
    dog = StepWatchdog.from_env(name="decode_tick")
    try:
        assert dog is not None and dog.name == "decode_tick"
        assert dog.min_interval_s == 30.0
        assert dog._thread is not None and dog._thread.daemon
    finally:
        dog.stop()


def test_dump_all_stacks_includes_current_thread():
    out = dump_all_stacks()
    assert "test_dump_all_stacks_includes_current_thread" in out
    assert "MainThread" in out


# -- engine integration: save -> die -> resume --------------------------


def test_resume_determinism_after_injected_kill(tmp_path, monkeypatch):
    """The tentpole drill, in-process: per-step losses after a
    kill -> restore are identical to the uninterrupted run, and the
    dataloader fast-forward matches the restored step."""
    monkeypatch.delenv("PFX_FAULTS", raising=False)

    def run(tag, max_steps, **over):
        losses = {}
        cfg, engine, loader = _build(
            tmp_path, **{"Engine.max_steps": max_steps,
                         "Engine.logging_freq": 1, **over})
        orig = engine.module.training_step_end

        def capture(log):
            losses[log["batch"]] = log["loss"]
            orig(log)

        engine.module.training_step_end = capture
        return cfg, engine, loader, losses

    cfg, engine, loader, base = run("base", 6)
    engine.fit(epoch=1, train_data_loader=loader)
    assert sorted(base) == [1, 2, 3, 4, 5, 6]

    out2 = str(tmp_path / "out_chaos")
    monkeypatch.setenv("PFX_FAULTS", "kill@step=5")
    monkeypatch.setenv("PFX_FAULTS_MODE", "raise")
    _, chaos_engine, loader, chaos = run(
        "chaos", 6, **{"Engine.save_load.output_dir": out2,
                       "Engine.save_load.save_steps": 2})
    with pytest.raises(InjectedKill):
        chaos_engine.fit(epoch=1, train_data_loader=loader)
    assert sorted(chaos) == [1, 2, 3, 4, 5]
    for s in chaos:   # same trajectory up to the kill
        assert chaos[s] == base[s]

    monkeypatch.delenv("PFX_FAULTS")
    cfg3, resumed, loader, res = run(
        "resume", 6, **{"Engine.save_load.output_dir": out2,
                        "Engine.save_load.ckpt_dir": out2,
                        "Engine.save_load.save_steps": 2})
    assert int(resumed.state["step"]) == 4   # newest durable save
    assert resumed._load_recovery["consumed_samples"] == \
        4 * cfg3.Global.global_batch_size
    resumed.fit(epoch=1, train_data_loader=loader)
    assert sorted(res) == [5, 6]
    assert res[5] == base[5] and res[6] == base[6]


def test_corrupted_newest_checkpoint_falls_back(tmp_path, monkeypatch):
    """corrupt_ckpt chaos case: the newest checkpoint fails
    verification, the engine restores its predecessor, and the
    demotion is recorded."""
    monkeypatch.delenv("PFX_FAULTS", raising=False)
    cfg, engine, loader = _build(
        tmp_path, **{"Engine.max_steps": 4,
                     "Engine.save_load.save_steps": 2})
    engine.fit(epoch=1, train_data_loader=loader)
    out = str(tmp_path / "out")
    newest = ckpt.latest_checkpoint(out)
    assert newest.endswith("step_4")
    FaultInjector("corrupt_ckpt@save=1",
                  kill_mode="raise").fire("save", 1, path=newest)

    # resolve-stage: a fresh engine skips the corrupt dir entirely
    cfg2, engine2, _ = _build(
        tmp_path, **{"Engine.max_steps": 4,
                     "Engine.save_load.ckpt_dir": out})
    assert int(engine2.state["step"]) == 2

    # load-stage: an explicit path demotes through load_checkpoint
    rec = Recorder()
    abstract = __import__("jax").tree.map(
        lambda x: x, engine2.state)   # concrete state as template
    state, meta = ckpt.load_checkpoint(newest, abstract,
                                       fallback_dir=out, recorder=rec)
    assert meta["step"] == 2
    (ev,) = rec.of("ckpt_fallback")
    assert ev["stage"] == "load" and ev["rejected"] == newest
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_checkpoint(newest, abstract, fallback_dir=None)


def test_kill_mid_async_save_resumes_from_previous(tmp_path,
                                                   monkeypatch):
    """Kill-mid-async-save chaos case: dying after the TensorStore
    write started but before the manifest committed leaves a torn
    (manifest-less) dir that resume must skip in favor of the previous
    committed checkpoint."""
    monkeypatch.delenv("PFX_FAULTS", raising=False)
    monkeypatch.setenv("PFX_FAULTS", "kill@step=5")
    monkeypatch.setenv("PFX_FAULTS_MODE", "raise")
    out = str(tmp_path / "out_async")
    cfg, engine, loader = _build(
        tmp_path, **{"Engine.max_steps": 6,
                     "Engine.save_load.output_dir": out,
                     "Engine.save_load.save_steps": 2,
                     "Engine.save_load.async_save": True})
    with pytest.raises(InjectedKill):
        engine.fit(epoch=1, train_data_loader=loader)
    # the step-4 save is still pending its manifest commit; simulate
    # the kill landing before that commit: let the bytes finish but
    # DROP the pending manifest instead of writing it
    assert ckpt._PENDING_MANIFEST is not None
    ckpt._ASYNC_CKPTR.wait_until_finished()
    ckpt._PENDING_MANIFEST = None
    torn = os.path.join(out, "epoch_0_step_4")
    assert os.path.isdir(torn)
    assert "manifest" in ckpt.verify_checkpoint(torn)

    monkeypatch.delenv("PFX_FAULTS")
    cfg2, resumed, _ = _build(
        tmp_path, **{"Engine.max_steps": 6,
                     "Engine.save_load.output_dir": out,
                     "Engine.save_load.ckpt_dir": out})
    assert int(resumed.state["step"]) == 2   # step-4 dir distrusted
    assert os.path.isdir(torn)               # skipped, not deleted


def test_engine_wires_watchdog_and_injector_from_env(tmp_path,
                                                     monkeypatch):
    """PFX_WATCHDOG/PFX_FAULTS reach the Engine: a hang fault sleeps
    inside the armed window, exactly the shape the watchdog times."""
    monkeypatch.setenv("PFX_WATCHDOG", "1")
    monkeypatch.setenv("PFX_FAULTS", "hang@step=1:0.01s")
    cfg, engine, loader = _build(tmp_path,
                                 **{"Engine.max_steps": 1})
    try:
        assert engine._watchdog is not None
        assert engine._watchdog.name == "train_step"
        assert engine._faults is not None
        engine.fit(epoch=1, train_data_loader=loader)
        assert engine._faults._faults[0].fired   # hang slept in-loop
    finally:
        engine._watchdog.stop()


def test_engine_keep_last_k_gc(tmp_path, monkeypatch):
    """save_load.keep_last_k bounds on-disk checkpoints through the
    engine's save path (default: unlimited retention)."""
    monkeypatch.delenv("PFX_FAULTS", raising=False)
    cfg, engine, loader = _build(
        tmp_path, **{"Engine.max_steps": 3,
                     "Engine.save_load.save_steps": 1,
                     "Engine.save_load.keep_last_k": 1})
    assert engine.keep_last_k == 1
    engine.fit(epoch=1, train_data_loader=loader)
    out = str(tmp_path / "out")
    steps = sorted(d for d in os.listdir(out)
                   if ckpt._STEP_DIR.match(d))
    assert steps == ["epoch_0_step_3"]
