"""Parity and dispatch probes for the overlapped tensor-parallel
collective matmuls (ops/collective_matmul.py).

The ISSUE-2 acceptance contract: `all_gather_matmul` /
`matmul_reduce_scatter` match the plain GSPMD lowering — forward AND
grads through the custom VJPs — to fp32 tolerance for mp in {2, 4},
and a non-divisible shape exercises the model-level fallback. The
dispatch rows mirror docs/tensor_parallel.md.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import (
    GPTConfig, GPTForPretraining, cross_entropy_loss,
)
from paddlefleetx_tpu.ops.collective_matmul import (
    all_gather_matmul, matmul_reduce_scatter, mp_ring_viable,
)
from paddlefleetx_tpu.parallel import (
    TopologyConfig, build_mesh, make_sharding_rules,
)
from paddlefleetx_tpu.parallel.mesh import set_mesh


def _mesh(mp):
    # 8 CPU devices: mp4 x dp2 and mp2 x dp2 x fsdp2
    kw = {"mp_degree": mp, "dp_degree": 2}
    if mp == 2:
        kw["sharding_degree"] = 2
    return build_mesh(TopologyConfig(**kw, sequence_parallel=True))


def _rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


# -- op-level parity: forward and grads vs the plain lowering ---------

@pytest.mark.parametrize("mp", [2, 4])
def test_all_gather_matmul_parity(mp):
    mesh = _mesh(mp)
    rng = np.random.default_rng(0)
    x, w = _rand(rng, 4, 8, 6), _rand(rng, 6, 12)

    def ring(x, w):
        y = all_gather_matmul(x, w, mesh)
        return jnp.sum(jnp.sin(y)), y

    def plain(x, w):
        y = jnp.einsum("bsk,kn->bsn", x, w)
        return jnp.sum(jnp.sin(y)), y

    with mesh:
        (loss, y), grads = jax.jit(jax.value_and_grad(
            ring, argnums=(0, 1), has_aux=True))(x, w)
    (ref_loss, ref_y), ref_grads = jax.jit(jax.value_and_grad(
        plain, argnums=(0, 1), has_aux=True))(x, w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref_y),
                               atol=1e-5)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for g, ref in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=1e-4)


@pytest.mark.parametrize("mp", [2, 4])
def test_all_gather_matmul_multidim_feature(mp):
    # the fused-qkv shape: w [k, 3, heads, hd], ring shard on heads
    mesh = _mesh(mp)
    rng = np.random.default_rng(1)
    x, w = _rand(rng, 4, 8, 6), _rand(rng, 6, 3, 4, 5)

    def ring(x, w):
        return jnp.sum(jnp.sin(
            all_gather_matmul(x, w, mesh, w_shard_dim=1)))

    def plain(x, w):
        return jnp.sum(jnp.sin(jnp.einsum("bsk,kthd->bsthd", x, w)))

    with mesh:
        loss, grads = jax.jit(jax.value_and_grad(
            ring, argnums=(0, 1)))(x, w)
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        plain, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for g, ref in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=1e-4)


@pytest.mark.parametrize("mp", [2, 4])
@pytest.mark.parametrize("contract_ndim", [1, 2])
def test_matmul_reduce_scatter_parity(mp, contract_ndim):
    mesh = _mesh(mp)
    rng = np.random.default_rng(2)
    if contract_ndim == 1:
        x, w = _rand(rng, 4, 8, 8), _rand(rng, 8, 10)
        ref_eq = "bsk,kn->bsn"
    else:
        # the out-proj shape: x [b, s, heads, hd] contracting both
        x, w = _rand(rng, 4, 8, 4, 3), _rand(rng, 4, 3, 10)
        ref_eq = "bshd,hdn->bsn"

    def ring(x, w):
        return jnp.sum(jnp.cos(matmul_reduce_scatter(
            x, w, mesh, contract_ndim=contract_ndim)))

    def plain(x, w):
        return jnp.sum(jnp.cos(jnp.einsum(ref_eq, x, w)))

    with mesh:
        loss, grads = jax.jit(jax.value_and_grad(
            ring, argnums=(0, 1)))(x, w)
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        plain, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for g, ref in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=1e-4)


# -- dispatch probes: the docs/tensor_parallel.md fallback rows -------

def test_mp_ring_viable_rows():
    mesh = _mesh(4)                       # mp4 x dp2: dataflow size 2
    assert mp_ring_viable(mesh, 4, 8, (4,))
    assert not mp_ring_viable(None, 4, 8, (4,))          # no mesh
    assert not mp_ring_viable(mesh, 4, 7, (4,))          # seq % mp
    assert not mp_ring_viable(mesh, 3, 8, (4,))          # batch % df
    assert not mp_ring_viable(mesh, 4, 8, (6,))          # dim % mp
    assert not mp_ring_viable(mesh, 1, 8, (4,))          # init sample
    assert not mp_ring_viable(mesh, 4, 1, (4,))          # decode step
    mp1 = build_mesh(TopologyConfig(dp_degree=8))
    assert not mp_ring_viable(mp1, 8, 8, (4,))           # mp == 1


def test_param_tree_identical_with_and_without_knob():
    """_CollectiveDense must create the exact DenseGeneral tree —
    names, shapes, logical axes — so checkpoints and abstract init
    are knob-independent."""
    base = dict(vocab_size=64, hidden_size=16, num_layers=2,
                num_attention_heads=4, max_position_embeddings=32,
                sequence_parallel=True)
    ids = jnp.zeros((1, 8), jnp.int32)

    def shapes(cfg):
        v = jax.eval_shape(GPTForPretraining(cfg).init,
                           {"params": jax.random.key(0)}, ids)
        return jax.tree.map(
            lambda x: (x.value.shape, x.names)
            if isinstance(x, nn.Partitioned) else x.shape,
            v, is_leaf=lambda x: isinstance(x, nn.Partitioned))

    on = shapes(GPTConfig(**base, use_collective_matmul=True))
    off = shapes(GPTConfig(**base))
    assert jax.tree.structure(on) == jax.tree.structure(off)
    assert jax.tree.leaves(on) == jax.tree.leaves(off)


def test_model_falls_back_on_indivisible_seq():
    """seq=14 does not divide mp=4: every site must take the plain
    path and still match the single-device reference exactly."""
    kw = dict(vocab_size=64, hidden_size=16, num_layers=2,
              num_attention_heads=4, max_position_embeddings=32,
              hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, 64, (8, 14)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 64, (8, 14)), jnp.int32)
    mask = jnp.ones((8, 14), jnp.float32)

    ref_model = GPTForPretraining(GPTConfig(**kw))
    variables = ref_model.init({"params": jax.random.key(0)},
                               jnp.zeros((1, 8), jnp.int32))
    params = nn.meta.unbox(variables)["params"]
    ref_loss = cross_entropy_loss(
        ref_model.apply({"params": params}, ids), labels, mask)

    cfg = GPTConfig(**kw, sequence_parallel=True,
                    use_collective_matmul=True)
    topo = TopologyConfig(mp_degree=4, dp_degree=2,
                          sequence_parallel=True)
    mesh = build_mesh(topo)
    set_mesh(mesh)
    model = GPTForPretraining(cfg)
    with mesh, nn.logical_axis_rules(list(make_sharding_rules(topo))):
        p = jax.device_put(params)
        loss = jax.jit(lambda p: cross_entropy_loss(
            model.apply({"params": p}, ids), labels, mask))(p)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
