import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.gpt import (
    GPTConfig, GPTForPretraining, GPTModel, cross_entropy_loss,
)

TINY = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                 num_attention_heads=4, max_position_embeddings=64,
                 hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


def _init(model, cfg=TINY, batch=2, seq=16):
    ids = jnp.zeros((batch, seq), jnp.int32)
    variables = model.init({"params": jax.random.key(0)}, ids)
    return variables, ids


def test_forward_shapes_and_dtype():
    model = GPTForPretraining(TINY)
    variables, ids = _init(model)
    logits = model.apply(variables, ids)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32


def test_param_count_345m_formula():
    """Sanity: parameter count matches the analytic transformer formula."""
    cfg = TINY
    variables, _ = _init(GPTForPretraining(cfg))
    n = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))
    h, L, v, p, f = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                     cfg.max_position_embeddings, cfg.ffn_hidden_size)
    per_layer = (3 * h * h + 3 * h) + (h * h + h) \
        + (h * f + f) + (f * h + h) + 4 * h
    expect = v * h + p * h + L * per_layer + 2 * h
    assert n == expect


def test_causality():
    """Changing a future token must not affect earlier logits."""
    model = GPTForPretraining(TINY)
    variables, _ = _init(model)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (1, 16)), jnp.int32)
    ids2 = ids.at[0, 10].set((ids[0, 10] + 1) % 128)
    a = model.apply(variables, ids)
    b = model.apply(variables, ids2)
    np.testing.assert_allclose(np.asarray(a[0, :10]), np.asarray(b[0, :10]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(a[0, 10:]), np.asarray(b[0, 10:]))


def test_scan_matches_unrolled():
    """nn.scan over layers == python-loop layers, given equal weights."""
    cfg_scan = TINY
    cfg_loop = GPTConfig(**{**vars(TINY), "scan_layers": False})
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, 128, (2, 8)), jnp.int32)
    m_scan, m_loop = GPTModel(cfg_scan), GPTModel(cfg_loop)
    v_scan = m_scan.init(jax.random.key(0), ids)
    # transplant scanned (stacked) weights into the unrolled layout
    p = v_scan["params"]
    loop_params = {"embeddings": p["embeddings"],
                   "final_norm": p["final_norm"]}
    stacked = p["decoder"]
    for i in range(cfg_loop.num_layers):
        loop_params[f"decoder_{i}"] = jax.tree.map(
            lambda x: x[i], stacked)
    out_scan = m_scan.apply(v_scan, ids)
    out_loop = m_loop.apply({"params": loop_params}, ids)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_loop),
                               atol=1e-5)


def test_recompute_granularities_same_loss_and_grads():
    base = GPTForPretraining(TINY)
    variables, _ = _init(base)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    mask = jnp.ones((2, 16), jnp.float32)

    def loss_fn(cfg):
        model = GPTForPretraining(cfg)

        def f(params):
            logits = model.apply({"params": params}, ids)
            return cross_entropy_loss(logits, labels, mask)
        return jax.value_and_grad(f)(variables["params"])

    ref_loss, ref_grad = loss_fn(TINY)
    for gran in ("full", "full_attn", "core_attn", "save_dots"):
        cfg = GPTConfig(**{**vars(TINY), "use_recompute": True,
                           "recompute_granularity": gran})
        loss, grad = loss_fn(cfg)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5),
            ref_grad, grad)


def test_kv_cache_decode_matches_full_forward():
    """Prefill + step-by-step cached decode == one full forward."""
    cfg = GPTConfig(**{**vars(TINY), "scan_layers": True})
    model = GPTForPretraining(cfg)
    variables, _ = _init(model)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, 128, (1, 12)), jnp.int32)

    full = model.apply(variables, ids)

    prefix, rest = ids[:, :8], ids[:, 8:]
    logits, mutated = model.apply(
        variables, prefix, use_cache=True, mutable=["cache"])
    outs = [logits]
    cache = mutated["cache"]
    for t in range(rest.shape[1]):
        step = rest[:, t:t + 1]
        logits, mutated = model.apply(
            {**variables, "cache": cache}, step, use_cache=True,
            position_offset=8 + t, mutable=["cache"])
        cache = mutated["cache"]
        outs.append(logits)
    stitched = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(stitched), np.asarray(full),
                               atol=2e-4)


def test_cross_entropy_matches_naive():
    rng = np.random.default_rng(4)
    logits = jnp.asarray(rng.normal(size=(2, 4, 8)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 8, (2, 4)), jnp.int32)
    mask = jnp.asarray([[1, 1, 0, 1], [0, 1, 1, 1]], jnp.float32)
    got = cross_entropy_loss(logits, labels, mask)
    probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -np.take_along_axis(np.asarray(probs),
                              np.asarray(labels)[..., None], -1)[..., 0]
    expect = (nll * np.asarray(mask)).sum() / np.asarray(mask).sum()
    np.testing.assert_allclose(float(got), expect, rtol=1e-6)


def test_bf16_compute_keeps_fp32_params():
    cfg = GPTConfig(**{**vars(TINY), "dtype": "bfloat16"})
    model = GPTForPretraining(cfg)
    variables, ids = _init(model, cfg)
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32
    logits = model.apply(variables, ids)
    assert logits.dtype == jnp.bfloat16


def test_chunked_lm_loss_matches_unchunked():
    """loss_chunks: identical loss AND grads to the dense logits path
    (the [b,s,V] tensor just never materializes whole)."""
    from paddlefleetx_tpu.models.gpt import (
        GPTConfig, GPTForPretraining, cross_entropy_loss,
    )
    from paddlefleetx_tpu.models.gpt.model import chunked_lm_loss

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=32,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForPretraining(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 96, (2, 32)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    mask = jnp.asarray(rng.integers(0, 2, (2, 32)), jnp.float32)
    params = model.init({"params": jax.random.key(0)}, ids)["params"]

    def dense(p):
        return cross_entropy_loss(model.apply({"params": p}, ids),
                                  labels, mask)

    def chunked(p):
        return chunked_lm_loss(model, p, ids, labels, mask, chunks=4)

    ld, gd = jax.value_and_grad(dense)(params)
    lc, gc = jax.value_and_grad(chunked)(params)
    np.testing.assert_allclose(float(ld), float(lc), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        gd, gc)


def test_chunked_loss_through_module_and_mesh():
    """Model.loss_chunks flows config -> module -> sharded loss on the
    8-device mesh."""
    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.parallel import (
        TopologyConfig, build_mesh, make_sharding_rules,
    )
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    cfg = AttrDict({
        "Global": AttrDict({"seed": 1, "global_batch_size": None,
                            "local_batch_size": 8,
                            "micro_batch_size": 8}),
        "Engine": AttrDict({"max_steps": 1,
                            "mix_precision": AttrDict({})}),
        "Model": AttrDict({
            "module": "GPTModule", "name": "GPT", "vocab_size": 96,
            "hidden_size": 32, "num_layers": 2,
            "num_attention_heads": 4, "max_position_embeddings": 32,
            "hidden_dropout_prob": 0.0,
            "attention_probs_dropout_prob": 0.0, "loss_chunks": 4,
        }),
        "Distributed": AttrDict({"dp_degree": 2, "mp_degree": 4,
                                 "sharding": AttrDict({})}),
        "Optimizer": AttrDict({"name": "AdamW",
                               "lr": AttrDict({"learning_rate": 1e-4})}),
        "Data": AttrDict({}),
    })
    process_configs(cfg, nranks=8)
    module = build_module(cfg)
    assert module.model_config.loss_chunks == 4
    topo = TopologyConfig.from_config(cfg)
    mesh = build_mesh(topo)
    rules = make_sharding_rules(topo)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 96, (8, 32)), jnp.int32)
    batch = (ids, None, jnp.roll(ids, -1, 1),
             jnp.ones((8, 32), jnp.float32))
    params = module.model.init({"params": jax.random.key(0)},
                               ids)["params"]
    with mesh, nn.logical_axis_rules(list(rules)):
        loss = jax.jit(lambda p: module.loss_fn(
            p, batch, jax.random.key(1), train=False))(params)
    assert np.isfinite(float(loss))


def _tiny_module(**model_kw):
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs
    model = {
        "module": "GPTModule", "name": "GPT", "vocab_size": 96,
        "hidden_size": 32, "num_layers": 2,
        "num_attention_heads": 4, "max_position_embeddings": 32,
        "hidden_dropout_prob": 0.0,
        "attention_probs_dropout_prob": 0.0,
    }
    model.update(model_kw)
    cfg = AttrDict({
        "Global": AttrDict({"seed": 1, "global_batch_size": None,
                            "local_batch_size": 2,
                            "micro_batch_size": 2}),
        "Engine": AttrDict({"max_steps": 1,
                            "mix_precision": AttrDict({})}),
        "Model": AttrDict(model),
        "Distributed": AttrDict({"sharding": AttrDict({})}),
        "Optimizer": AttrDict({"name": "AdamW",
                               "lr": AttrDict({"learning_rate": 1e-4})}),
        "Data": AttrDict({}),
    })
    process_configs(cfg, nranks=1)
    return build_module(cfg)


def test_flash_dropout_long_seq_training_refused():
    """VERDICT r3 #5: TRAINING with flash + attention dropout at long
    sequence must fail loudly — it would silently fall back to dense
    XLA attention and OOM at s >= 8k with no hint why. Construction
    stays legal (eval/generation run deterministic and keep the
    kernel); the refusal lives at the training entry."""
    m = _tiny_module(use_flash_attention=True,
                     attention_probs_dropout_prob=0.1,
                     max_position_embeddings=8192)
    long_tokens = jnp.zeros((2, 8192), jnp.int32)
    with pytest.raises(ValueError, match="dense XLA attention"):
        m._pp_setup(long_tokens, train=True)
    m._pp_setup(long_tokens, train=False)  # eval path unaffected
    # the gate keys on the ACTUAL sequence length: fine-tuning the
    # same long-context checkpoint at short sequence is the benign
    # documented operating point and must pass
    m._pp_setup(jnp.zeros((2, 1024), jnp.int32), train=True)


def test_ring_cp_dropout_training_refused_any_length():
    m = _tiny_module(context_parallel=True,
                     context_parallel_algo="ring",
                     attention_probs_dropout_prob=0.1)
    tokens = jnp.zeros((2, 8), jnp.int32)
    with pytest.raises(ValueError, match="ring"):
        m._pp_setup(tokens, train=True)
    m._pp_setup(tokens, train=False)


def test_flash_dropout_short_seq_warns_but_constructs(monkeypatch):
    """The reference's 345M recipe (dropout 0.1, s=1024) stays valid:
    dense fallback is a documented, benign operating point there —
    but it must WARN (the project logger has propagate=False, so
    assert on the call itself). Pin the kernel-dropout gate OFF: the
    gate is self-certifying (a committed chip-cert artifact flips it
    on), and this test asserts the UNcertified behavior."""
    from unittest import mock

    from paddlefleetx_tpu.utils.log import logger
    monkeypatch.setenv("PFX_FLASH_DROPOUT", "0")
    with mock.patch.object(logger, "warning") as warn:
        cfg = GPTConfig(use_flash_attention=True,
                        attention_probs_dropout_prob=0.1,
                        max_position_embeddings=1024)
    assert cfg.use_flash_attention
    assert warn.called
    assert "dense XLA path" in warn.call_args[0][0]


def test_flash_dropout_certified_gate_silences_warning(monkeypatch):
    """With in-kernel dropout certified (gate on) there is no dense
    fallback at the kernel-capable shapes and nothing to warn about."""
    from unittest import mock

    from paddlefleetx_tpu.utils.log import logger
    monkeypatch.setenv("PFX_FLASH_DROPOUT", "1")
    with mock.patch.object(logger, "warning") as warn:
        GPTConfig(use_flash_attention=True,
                  attention_probs_dropout_prob=0.1,
                  max_position_embeddings=1024)
    assert not warn.called


def test_ulysses_cp_dropout_allowed_long_seq():
    """Ulysses attention is dense per head-shard BY DESIGN (its
    documented O(s^2/cp) trade), so dropout there is supported — both
    at construction and at the training entry."""
    m = _tiny_module(context_parallel=True,
                     context_parallel_algo="ulysses",
                     use_flash_attention=True,
                     attention_probs_dropout_prob=0.1,
                     max_position_embeddings=8192)
    m._pp_setup(jnp.zeros((2, 8), jnp.int32), train=True)
