"""Pallas grouped expert GEMM semantics (ops/pallas/grouped_matmul.py),
validated on CPU via the Pallas interpreter — forward/backward against
the dense batched-matmul reference, the empty-group skip, the
weight-replication (rep > 1) indexing, and the kernel-admission
(fallback) contract. The MoE-layer-level parity matrix lives in
tests/test_moe.py."""

import os

os.environ["PFX_PALLAS_INTERPRET"] = "1"

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.ops.pallas.grouped_matmul import grouped_matmul


def _case(g=6, gw=3, c=8, k=16, n=24, seed=0, fill=0.6):
    """Random [G, C, K] groups with capacity-padded (zeroed) rows and
    a per-group live count; rep = G // Gw rows share each weight."""
    rng = np.random.default_rng(seed)
    counts = rng.integers(0, c + 1, size=g).astype(np.int32)
    counts[: max(1, int(g * (1 - fill)))] = 0  # guarantee empty groups
    rng.shuffle(counts)
    x = rng.normal(size=(g, c, k)).astype(np.float32)
    mask = np.arange(c)[None, :, None] < counts[:, None, None]
    x = x * mask  # rows past counts[g] are zero (the kernel contract)
    w = rng.normal(size=(gw, k, n)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(w), jnp.asarray(counts)


def _dense_ref(x, w):
    rep = x.shape[0] // w.shape[0]
    wg = jnp.repeat(w, rep, axis=0)
    return jnp.einsum("gck,gkn->gcn", x, wg)


@pytest.mark.parametrize("g,gw", [(4, 4), (6, 3), (8, 2)])
def test_forward_matches_dense(g, gw):
    x, w, counts = _case(g=g, gw=gw)
    got = grouped_matmul(x, w, counts)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_dense_ref(x, w)),
                               atol=1e-5, rtol=1e-5)


def test_empty_groups_produce_zero_blocks():
    x, w, counts = _case(fill=0.3)
    got = np.asarray(grouped_matmul(x, w, counts))
    for gi in np.nonzero(np.asarray(counts) == 0)[0]:
        np.testing.assert_array_equal(got[gi], 0.0)


def test_all_groups_empty_is_all_zero():
    x, w, counts = _case()
    zero = jnp.zeros_like(counts)
    got = grouped_matmul(jnp.zeros_like(x), w, zero)
    np.testing.assert_array_equal(np.asarray(got), 0.0)


def test_gradients_match_dense():
    """The custom VJP (dx via the transposed forward kernel, dw via the
    per-expert accumulation kernel) must match autodiff through the
    dense reference — including zero dx/dw contributions from the
    skipped empty groups, whose cotangent rows are zero under the MoE
    combine contract."""
    x, w, counts = _case(g=6, gw=3, fill=0.5)
    live = (jnp.arange(x.shape[1])[None, :, None]
            < counts[:, None, None]).astype(x.dtype)

    def loss(fn):
        # cube to make the grads weight-dependent; mask the padded
        # rows exactly as the gate-weighted combine does
        return lambda xx, ww: ((fn(xx, ww) * live) ** 3).sum()

    ref_l, (ref_dx, ref_dw) = jax.value_and_grad(
        loss(_dense_ref), argnums=(0, 1))(x, w)
    got_l, (got_dx, got_dw) = jax.value_and_grad(
        loss(lambda xx, ww: grouped_matmul(xx, ww, counts)),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(float(got_l), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got_dx), np.asarray(ref_dx),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(ref_dw),
                               atol=1e-4, rtol=1e-4)


def test_fp32_accumulation_under_bf16_inputs():
    """bf16 in, bf16 out, but the contraction accumulates in fp32
    scratch: the result must track the fp32 reference to bf16
    resolution, not drift with K."""
    x, w, counts = _case(k=256, n=8)
    xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    got = grouped_matmul(xb, wb, counts)
    assert got.dtype == jnp.bfloat16
    ref = _dense_ref(x, w)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref),
        atol=0.1, rtol=0.05)


def test_runs_under_jit():
    x, w, counts = _case()
    got = jax.jit(grouped_matmul)(x, w, counts)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_dense_ref(x, w)),
                               atol=1e-5, rtol=1e-5)


def test_block_shrink_handles_indivisible_dims():
    # n=24, k=16 don't divide the 128/512 defaults — _block shrinks
    x, w, counts = _case(c=5, k=12, n=20)
    got = grouped_matmul(x, w, counts)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_dense_ref(x, w)),
                               atol=1e-5, rtol=1e-5)


def test_shape_rejection_is_notimplemented():
    """Kernel admission failures must raise NotImplementedError — the
    MoE layer catches exactly that to fall back to its XLA expert
    einsums (counted moe/fallback/pallas_rejected)."""
    x, w, counts = _case(g=6, gw=3)
    with pytest.raises(NotImplementedError):
        grouped_matmul(x[0], w, counts)             # x not 3D
    with pytest.raises(NotImplementedError):
        grouped_matmul(x, jnp.concatenate([w, w[:1]]),
                       counts)                      # Gw does not divide G
    with pytest.raises(NotImplementedError):
        grouped_matmul(x, w, counts[:-1])           # counts length
    with pytest.raises(NotImplementedError):
        grouped_matmul(x, jnp.swapaxes(w, 1, 2), counts)  # K mismatch
    with pytest.raises(NotImplementedError):
        grouped_matmul(x, w, counts.astype(jnp.float32))  # counts dtype
