"""Flash-attention kernel semantics, validated on CPU via the Pallas
interpreter (the real-TPU path is exercised by bench.py and the
on-device verification runs)."""

import os

os.environ["PFX_PALLAS_INTERPRET"] = "1"

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.ops.attention import _xla_attention
from paddlefleetx_tpu.ops.pallas.flash_attention import flash_attention


def _rand(b=1, s=256, h=2, d=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_xla(causal):
    q, k, v = _rand()
    ref = _xla_attention(q, k, v, None, causal, 0, 0.0, None, True, True)
    got = flash_attention(q, k, v, causal=causal, block_q=128,
                          block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_grads_match_xla():
    q, k, v = _rand(s=256)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=128,
                                block_kv=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (_xla_attention(q, k, v, None, True, 0, 0.0, None, True,
                               True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_fused_multiblock_backward_grads_match_xla(causal):
    """sq=1024 with 512 blocks routes the backward through the fused
    q-resident one-pass kernel (num_q=2, within the VMEM budget);
    its gradients must match the XLA oracle like the split pair's."""
    from paddlefleetx_tpu.ops.pallas import flash_attention as fa

    q, k, v = _rand(s=1024)
    # the shape gate really selects the fused path (dispatch helper
    # takes [bh, s, d] arrays) ...
    qq = jnp.zeros((2, 1024, 64), jnp.float32)
    assert fa._flash_backward_fused(
        qq, qq, qq, qq, jnp.zeros((2, 1024, 1), jnp.float32),
        jnp.zeros((2, 1024, 1), jnp.float32), 1.0, causal, 0) \
        is not None
    # ... and beyond the resident budget it declines
    big = jnp.zeros((1, 16384, 64), jnp.float32)
    assert fa._flash_backward_fused(
        big, big, big, big, jnp.zeros((1, 16384, 1), jnp.float32),
        jnp.zeros((1, 16384, 1), jnp.float32), 1.0, causal, 0) is None

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=512,
                                block_kv=512) ** 2).sum()

    def loss_ref(q, k, v):
        return (_xla_attention(q, k, v, None, causal, 0, 0.0, None,
                               True, True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)


def test_with_lse_matches_dense_including_lse_grads():
    """flash_attention_with_lse: the lse output matches a dense
    logsumexp, and gradients flow correctly through BOTH outputs (the
    lse cotangent folds into the backward kernels' delta term)."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_attention_with_lse,
    )
    q, k, v = _rand(s=256)
    d = q.shape[-1]

    def dense_out_lse(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * d ** -0.5
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask, s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)      # [b,h,q]
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        return out, lse

    out, lse = flash_attention_with_lse(q, k, v, block_q=128,
                                        block_kv=128)
    ref_out, ref_lse = dense_out_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        o, s = flash_attention_with_lse(q, k, v, block_q=128,
                                        block_kv=128)
        return (o ** 2).sum() + (jnp.sin(s)).sum()

    def loss_ref(q, k, v):
        o, s = dense_out_lse(q, k, v)
        return (o ** 2).sum() + (jnp.sin(s)).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_with_flash_blocks_matches_dense(causal):
    """The flash-per-block ring path == dense attention, fwd and bwd
    (diagonal/full/dead block dispatch + lse streaming combination)."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    from paddlefleetx_tpu.ops.ring_attention import (
        ring_attention_sharded,
    )
    from paddlefleetx_tpu.parallel import TopologyConfig, build_mesh

    rng = np.random.default_rng(9)
    b, s, h, d = 1, 512, 2, 64              # 128-token blocks on cp=4
    q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
               for _ in range(3))
    topo = TopologyConfig(cp_degree=4)
    mesh = build_mesh(topo, devices=jax.devices()[:4])

    want = dot_product_attention(q, k, v, causal=causal)
    got = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                 use_flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)

    def loss(fn):
        def f(q, k, v):
            return (fn(q, k, v) ** 2).sum()
        return jax.grad(f, argnums=(0, 1, 2))

    gf = loss(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh, causal=causal, use_flash=True))(q, k, v)
    gr = loss(lambda q, k, v: dot_product_attention(
        q, k, v, causal=causal))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_combined_backward_multi_kv_blocks_matches_xla():
    """The num_q==1 combined backward kernel (single q block, several
    kv blocks — the training hot path's regime) must reproduce XLA
    gradients: exercises dq accumulation across kv blocks and the
    per-ki direct dk/dv writes, which the split-kernel tests never
    reach."""
    q, k, v = _rand(s=256)

    def loss_flash(q, k, v):
        # block_q=256 -> num_q=1, block_kv=128 -> num_kv=2
        return (flash_attention(q, k, v, block_q=256,
                                block_kv=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (_xla_attention(q, k, v, None, True, 0, 0.0, None, True,
                               True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_bf16_training_dtype_matches_xla_within_tolerance():
    """Kernel vs XLA path at the TRAINING dtype (bf16 q/k/v, fp32
    accumulation in both): the kernel pre-scales q in bf16 (one extra
    rounding vs scaling fp32 scores), so the paths are close but not
    bit-equal. Tolerances are set from the real-chip measurement
    (v5e, b=4/s=1024/h=8/d=64: fwd max |diff| 0.016 at |out|~0.08
    mean, dq max |diff| 0.17 at sum-of-squares loss) with ~3x
    headroom; a regression in the scaling scheme would blow well
    past them."""
    rng = np.random.default_rng(5)
    shape = (2, 256, 2, 64)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
               for _ in range(3))
    ref = _xla_attention(q, k, v, None, True, 0, 0.0, None, True, True)
    got = flash_attention(q, k, v, causal=True, block_q=128,
                          block_kv=128)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               atol=5e-2, rtol=5e-2)

    def loss_flash(q):
        return (flash_attention(q, k, v, block_q=128,
                                block_kv=128).astype(jnp.float32)
                ** 2).sum()

    def loss_ref(q):
        return (_xla_attention(q, k, v, None, True, 0, 0.0, None, True,
                               True).astype(jnp.float32) ** 2).sum()

    gf = np.asarray(jax.grad(loss_flash)(q), np.float32)
    gr = np.asarray(jax.grad(loss_ref)(q), np.float32)
    np.testing.assert_allclose(gf, gr, atol=0.5, rtol=0.1)


def test_uneven_blocks_fall_back():
    q, k, v = _rand(s=100)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, block_q=64, block_kv=64)


def test_dispatch_falls_back_to_xla_on_unsupported():
    """ops.dot_product_attention must not crash when flash refuses."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    q, k, v = _rand(s=100)
    out = dot_product_attention(q, k, v, use_flash=True)
    ref = _xla_attention(q, k, v, None, True, 0, 0.0, None, True, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6)


def test_decode_matches_xla_and_ignores_garbage():
    """flash_decode == XLA cached-decode attention, and cache contents
    past the index never leak into the output."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import flash_decode
    rng = np.random.default_rng(3)
    b, S, h, d = 2, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    # tile-exact cache layout [b, h, d, S]
    k = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    for off in (0, 5, 130, 255):
        ref = _xla_attention(q, k, v, None, True, off, 0.0, None, True,
                             True, kv_cache_layout=True)
        got = flash_decode(q, k, v, jnp.int32(off), block_kv=128)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)
        # garbage independence: mutate the cache beyond the offset
        k2 = k.at[..., off + 1:].set(1e3)
        v2 = v.at[..., off + 1:].set(-1e3)
        got2 = flash_decode(q, k2, v2, jnp.int32(off), block_kv=128)
        np.testing.assert_allclose(np.asarray(got2), np.asarray(got),
                                   atol=2e-6, rtol=2e-6)


def test_decode_works_under_jit_with_traced_offset():
    from paddlefleetx_tpu.ops.pallas.flash_attention import flash_decode
    rng = np.random.default_rng(4)
    b, S, h, d = 1, 128, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)

    @jax.jit
    def step(off):
        return flash_decode(q, k, v, off)

    a = step(jnp.int32(7))
    bb = step(jnp.int32(100))          # same trace, new offset
    ref_a = _xla_attention(q, k, v, None, True, 7, 0.0, None, True, True,
                           kv_cache_layout=True)
    ref_b = _xla_attention(q, k, v, None, True, 100, 0.0, None, True,
                           True, kv_cache_layout=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(ref_a),
                               atol=2e-6, rtol=2e-6)
    np.testing.assert_allclose(np.asarray(bb), np.asarray(ref_b),
                               atol=2e-6, rtol=2e-6)


def test_decode_dispatch_from_dot_product_attention():
    """dot_product_attention routes single-token cached decode to the
    kernel (use_flash) and falls back cleanly on odd shapes."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    rng = np.random.default_rng(5)
    b, S, h, d = 1, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    out = dot_product_attention(q, k, v, causal=True,
                                query_offset=jnp.int32(17),
                                use_flash=True, kv_cache_layout=True)
    ref = _xla_attention(q, k, v, None, True, 17, 0.0, None, True, True,
                         kv_cache_layout=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    # head_dim the kernel rejects (not a sublane multiple) -> XLA
    # fallback, still correct
    q2 = q[..., :44]
    k2 = k[:, :, :44, :]
    v2 = v[:, :, :44, :]
    out2 = dot_product_attention(q2, k2, v2, causal=True,
                                 query_offset=jnp.int32(3),
                                 use_flash=True, kv_cache_layout=True)
    ref2 = _xla_attention(q2, k2, v2, None, True, 3, 0.0, None, True,
                          True, kv_cache_layout=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               atol=2e-6, rtol=2e-6)


def test_decode_with_leftpad_bias_matches_xla():
    """The decode kernel honors the generation loop's [b,1,1,S]
    additive left-pad bias."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    rng = np.random.default_rng(6)
    b, S, h, d = 2, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    # row 0 pads the first 3 slots, row 1 the first 120
    valid = np.ones((b, S), bool)
    valid[0, :3] = False
    valid[1, :120] = False
    bias = jnp.where(jnp.asarray(valid), 0.0, -1e9)[:, None, None, :]
    off = jnp.int32(130)
    out = dot_product_attention(q, k, v, bias=bias, causal=True,
                                query_offset=off, use_flash=True,
                                kv_cache_layout=True)
    ref = _xla_attention(q, k, v, bias, True, off, 0.0, None, True, True,
                         kv_cache_layout=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def _decode_batch(b=4, S=256, h=2, d=64, seed=11):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    return q, k, v


def test_flash_decode_ragged_matches_xla_per_row():
    """flash_decode_ragged with per-row cache lengths == the XLA
    per-row-offset oracle, and garbage past EACH row's length never
    leaks (the continuous-batching invariant: a fresh slot shares the
    tick with deep slots whose cache tails it must not read)."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode, flash_decode_ragged,
    )
    q, k, v = _decode_batch()
    offs = jnp.asarray([0, 5, 130, 255], jnp.int32)
    ref = _xla_attention(q, k, v, None, True, offs, 0.0, None, True,
                         True, kv_cache_layout=True)
    got = flash_decode_ragged(q, k, v, offs, block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    # garbage independence per row
    mask = np.arange(256)[None, :] > np.asarray(offs)[:, None]
    k2 = jnp.where(jnp.asarray(mask)[:, None, None, :], 1e3, k)
    v2 = jnp.where(jnp.asarray(mask)[:, None, None, :], -1e3, v)
    got2 = flash_decode_ragged(q, k2, v2, offs, block_kv=128)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(got),
                               atol=2e-6, rtol=2e-6)
    # all-equal lengths degenerate to the scalar kernel exactly
    uni = jnp.full((4,), 130, jnp.int32)
    np.testing.assert_allclose(
        np.asarray(flash_decode_ragged(q, k, v, uni, block_kv=128)),
        np.asarray(flash_decode(q, k, v, jnp.int32(130),
                                block_kv=128)),
        atol=2e-6, rtol=2e-6)


def test_flash_decode_ragged_under_jit_with_traced_offsets():
    """One compiled tick serves any slot-length vector (the serving
    decode loop retraces nothing as slots churn)."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode_ragged,
    )
    q, k, v = _decode_batch(b=2, S=128, seed=12)

    @jax.jit
    def step(offs):
        return flash_decode_ragged(q, k, v, offs)

    for offs in ([3, 100], [127, 0]):
        offs = jnp.asarray(offs, jnp.int32)
        ref = _xla_attention(q, k, v, None, True, offs, 0.0, None,
                             True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(step(offs)),
                                   np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)


def test_flash_decode_ragged_rejects_bad_offset_shapes():
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode_ragged,
    )
    q, k, v = _decode_batch(b=2, S=128, seed=13)
    with pytest.raises(NotImplementedError):
        flash_decode_ragged(q, k, v, jnp.zeros((3,), jnp.int32))
    with pytest.raises(NotImplementedError):
        flash_decode_ragged(q, k, v, jnp.zeros((2, 2), jnp.int32))


def test_ragged_decode_dispatch_and_counter():
    """dot_product_attention routes a [b] query_offset to the ragged
    kernel (counter `attention/flash_decode_ragged`), falls back to
    the identically-masked dense path on kernel-rejected shapes, and
    honors the [b,1,1,S] left-pad bias — the docs/inference.md decode
    dispatch matrix rows for ragged offsets."""
    from paddlefleetx_tpu.observability import metrics
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    q, k, v = _decode_batch(b=2, S=256, seed=14)
    offs = jnp.asarray([17, 200], jnp.int32)
    reg = metrics.get_registry()
    metrics.set_enabled(True)
    reg.reset()
    try:
        out = dot_product_attention(q, k, v, causal=True,
                                    query_offset=offs, use_flash=True,
                                    kv_cache_layout=True)
        assert reg.counter("attention/flash_decode_ragged") == 1
        assert reg.counter("attention/dense") == 0
        ref = _xla_attention(q, k, v, None, True, offs, 0.0, None,
                             True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)
        # left-pad bias rides along (row 1 pads its first 120 slots)
        valid = np.ones((2, 256), bool)
        valid[1, :120] = False
        bias = jnp.where(jnp.asarray(valid), 0.0, -1e9)[:, None, None, :]
        outb = dot_product_attention(q, k, v, bias=bias, causal=True,
                                     query_offset=offs, use_flash=True,
                                     kv_cache_layout=True)
        refb = _xla_attention(q, k, v, bias, True, offs, 0.0, None,
                              True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(outb), np.asarray(refb),
                                   atol=2e-6, rtol=2e-6)
        # head_dim the kernel rejects -> dense fallback, same per-row
        # masking
        reg.reset()
        q2, k2, v2 = q[..., :44], k[:, :, :44, :], v[:, :, :44, :]
        out2 = dot_product_attention(q2, k2, v2, causal=True,
                                     query_offset=offs, use_flash=True,
                                     kv_cache_layout=True)
        assert reg.counter("attention/fallback/kernel_rejected") == 1
        assert reg.counter("attention/dense") == 1
        ref2 = _xla_attention(q2, k2, v2, None, True, offs, 0.0, None,
                              True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                                   atol=2e-6, rtol=2e-6)
    finally:
        metrics.set_enabled(False)
        reg.reset()


def _paged_batch(b=4, h=4, d=64, page=128, pool=14, max_pages=3,
                 seed=21):
    """Random paged-decode inputs: global KV pool + a page table whose
    rows map distinct non-null pages (the allocator never maps page 0
    under a live position)."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(pool, h, d, page)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(pool, h, d, page)), jnp.float32)
    ids = rng.permutation(np.arange(1, pool))[:b * max_pages]
    pt = jnp.asarray(ids.reshape(b, max_pages), jnp.int32)
    return q, k, v, pt


def test_flash_decode_paged_matches_xla_gather():
    """flash_decode_paged (scalar-prefetch page-table walk) == the XLA
    oracle run on the gathered contiguous view — including rows whose
    live length stops mid-page, and rows sharing a physical page."""
    from paddlefleetx_tpu.ops.attention import _gather_kv_pages
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode_paged,
    )
    q, k, v, pt = _paged_batch()
    # row 3 shares row 0's first page (the COW/prefix-sharing shape)
    pt = pt.at[3, 0].set(pt[0, 0])
    offs = jnp.asarray([0, 130, 255, 383], jnp.int32)
    kg, vg = _gather_kv_pages(k, pt), _gather_kv_pages(v, pt)
    ref = _xla_attention(q, kg, vg, None, True, offs, 0.0, None, True,
                         True, kv_cache_layout=True)
    got = flash_decode_paged(q, k, v, offs, pt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    # pages past each row's length are never read: poison every pool
    # page the rows' live prefixes don't reach
    live = np.zeros(k.shape[0], bool)
    for i, off in enumerate(np.asarray(offs)):
        for j in range(int(off) // 128 + 1):
            live[int(pt[i, j])] = True
    poison = jnp.asarray(~live)[:, None, None, None]
    got2 = flash_decode_paged(q, jnp.where(poison, 1e3, k),
                              jnp.where(poison, -1e3, v), offs, pt)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(got),
                               atol=2e-6, rtol=2e-6)


def test_flash_decode_paged_identity_table_matches_ragged():
    """A pool laid out contiguously with an identity page table is the
    SAME logical cache as the PR-5 contiguous layout, so the paged
    kernel must reproduce flash_decode_ragged bit-for-tolerance."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode_paged, flash_decode_ragged,
    )
    b, S, page = 4, 256, 128
    m = S // page
    q, k, v = _decode_batch(b=b, S=S, seed=22)
    offs = jnp.asarray([0, 5, 130, 255], jnp.int32)
    # pool[1 + bi*m + j] holds row bi's logical page j
    def to_pool(t):
        t = np.asarray(t)                      # [b, h, d, S]
        pages = t.reshape(*t.shape[:3], m, page)
        pool = np.zeros((1 + b * m, t.shape[1], t.shape[2], page),
                        t.dtype)
        pool[1:] = pages.transpose(0, 3, 1, 2, 4).reshape(
            b * m, t.shape[1], t.shape[2], page)
        return jnp.asarray(pool)
    pt = jnp.asarray(
        1 + np.arange(b * m).reshape(b, m), jnp.int32)
    got = flash_decode_paged(q, to_pool(k), to_pool(v), offs, pt)
    ref = flash_decode_ragged(q, k, v, offs, block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def test_flash_decode_paged_rejects_bad_shapes():
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode_paged,
    )
    q, k, v, pt = _paged_batch(b=2, pool=5, max_pages=2, seed=23)
    offs = jnp.zeros((2,), jnp.int32)
    with pytest.raises(NotImplementedError):  # bias unsupported
        flash_decode_paged(q, k, v, offs, pt,
                           bias=jnp.zeros((2, 1, 1, 256)))
    with pytest.raises(NotImplementedError):  # bias + verify window
        flash_decode_paged(jnp.concatenate([q, q], 1), k, v, offs, pt,
                           bias=jnp.zeros((2, 1, 1, 256)))
    with pytest.raises(NotImplementedError):  # empty window
        flash_decode_paged(q[:, :0], k, v, offs, pt)
    with pytest.raises(NotImplementedError):  # offsets batch mismatch
        flash_decode_paged(q, k, v, jnp.zeros((3,), jnp.int32), pt)
    with pytest.raises(NotImplementedError):  # page_table not [b, m]
        flash_decode_paged(q, k, v, offs, pt[0])
    with pytest.raises(NotImplementedError):  # pool head mismatch
        flash_decode_paged(q, k[:, :2], v[:, :2], offs, pt)
    with pytest.raises(NotImplementedError):  # page not 128-tileable
        flash_decode_paged(q, k[..., :64], v[..., :64], offs, pt)


def test_paged_decode_dispatch_and_counter():
    """dot_product_attention routes (ragged offsets + page_table) to
    the paged kernel (counter `attention/flash_decode_paged`) and the
    kernel-rejected shapes to the dense gather fallback with identical
    per-row masking — the docs/inference.md paged dispatch row."""
    from paddlefleetx_tpu.observability import metrics
    from paddlefleetx_tpu.ops.attention import (
        _gather_kv_pages, dot_product_attention,
    )
    q, k, v, pt = _paged_batch(b=2, pool=7, max_pages=2, seed=24)
    offs = jnp.asarray([17, 200], jnp.int32)
    reg = metrics.get_registry()
    metrics.set_enabled(True)
    reg.reset()
    try:
        out = dot_product_attention(q, k, v, causal=True,
                                    query_offset=offs, use_flash=True,
                                    kv_cache_layout=True,
                                    page_table=pt)
        assert reg.counter("attention/flash_decode_paged") == 1
        assert reg.counter("attention/dense") == 0
        kg, vg = _gather_kv_pages(k, pt), _gather_kv_pages(v, pt)
        ref = _xla_attention(q, kg, vg, None, True, offs, 0.0, None,
                             True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)
        # head_dim the kernel rejects -> gather + dense, same masking
        reg.reset()
        q2, k2, v2 = q[..., :44], k[:, :, :44], v[:, :, :44]
        out2 = dot_product_attention(q2, k2, v2, causal=True,
                                     query_offset=offs, use_flash=True,
                                     kv_cache_layout=True,
                                     page_table=pt)
        assert reg.counter("attention/fallback/kernel_rejected") == 1
        assert reg.counter("attention/dense") == 1
        kg2, vg2 = _gather_kv_pages(k2, pt), _gather_kv_pages(v2, pt)
        ref2 = _xla_attention(q2, kg2, vg2, None, True, offs, 0.0,
                              None, True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                                   atol=2e-6, rtol=2e-6)
    finally:
        metrics.set_enabled(False)
        reg.reset()


def test_flash_decode_ragged_verify_window_matches_xla():
    """The speculative k-token VERIFY window: sq > 1 ragged decode ==
    the XLA per-row-offset oracle (query j of row i sees keys <=
    offs[i] + j — the within-window causal mask), garbage past each
    row's window never leaks, and a window of 1 degenerates to the
    single-token kernel exactly."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode_ragged,
    )
    rng = np.random.default_rng(31)
    b, S, h, d, W = 4, 256, 2, 64, 4
    q = jnp.asarray(rng.normal(size=(b, W, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    # rows whose windows start at 0, mid-block, a block edge, and the
    # last admissible start (offs + W - 1 == S - 1)
    offs = jnp.asarray([0, 5, 127, S - W], jnp.int32)
    ref = _xla_attention(q, k, v, None, True, offs, 0.0, None, True,
                         True, kv_cache_layout=True)
    got = flash_decode_ragged(q, k, v, offs, block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    # nothing past each row's LAST window position is ever read
    mask = np.arange(S)[None, :] > (np.asarray(offs)[:, None] + W - 1)
    k2 = jnp.where(jnp.asarray(mask)[:, None, None, :], 1e3, k)
    v2 = jnp.where(jnp.asarray(mask)[:, None, None, :], -1e3, v)
    got2 = flash_decode_ragged(q, k2, v2, offs, block_kv=128)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(got),
                               atol=2e-6, rtol=2e-6)
    # W = 1 is the original single-token kernel, column for column
    np.testing.assert_allclose(
        np.asarray(flash_decode_ragged(q[:, :1], k, v, offs,
                                       block_kv=128)),
        np.asarray(got[:, :1]), atol=2e-6, rtol=2e-6)


def test_flash_decode_paged_verify_window_matches_xla():
    """The verify window over the PAGED pool: same within-window
    causal mask through the page-table walk, validated against the
    XLA oracle on the gathered contiguous view — including a window
    that CROSSES a page boundary mid-run."""
    from paddlefleetx_tpu.ops.attention import _gather_kv_pages
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_decode_paged,
    )
    rng = np.random.default_rng(32)
    b, h, d, page, pool, mp, W = 4, 4, 64, 128, 14, 3, 4
    q = jnp.asarray(rng.normal(size=(b, W, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(pool, h, d, page)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(pool, h, d, page)), jnp.float32)
    ids = rng.permutation(np.arange(1, pool))[:b * mp]
    pt = jnp.asarray(ids.reshape(b, mp), jnp.int32)
    # row 1's window spans the page-0/page-1 boundary (126..129); row
    # 3 ends exactly at the table's last position
    offs = jnp.asarray([0, 126, 200, mp * page - W], jnp.int32)
    kg, vg = _gather_kv_pages(k, pt), _gather_kv_pages(v, pt)
    ref = _xla_attention(q, kg, vg, None, True, offs, 0.0, None, True,
                         True, kv_cache_layout=True)
    got = flash_decode_paged(q, k, v, offs, pt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    # pages no row's window reaches are never read
    live = np.zeros(pool, bool)
    for i, off in enumerate(np.asarray(offs)):
        for j in range((int(off) + W - 1) // page + 1):
            live[int(pt[i, j])] = True
    poison = jnp.asarray(~live)[:, None, None, None]
    got2 = flash_decode_paged(q, jnp.where(poison, 1e3, k),
                              jnp.where(poison, -1e3, v), offs, pt)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(got),
                               atol=2e-6, rtol=2e-6)


def test_verify_window_dispatch_and_counters():
    """dot_product_attention routes a short multi-token window with
    per-row offsets to the verify kernels (`attention/*_verify`
    counters), and a window past MAX_VERIFY_WINDOW — chunked
    prefill's shape — to the dense path, never the kernel."""
    from paddlefleetx_tpu.observability import metrics
    from paddlefleetx_tpu.ops.attention import (
        MAX_VERIFY_WINDOW, _gather_kv_pages, dot_product_attention,
    )
    rng = np.random.default_rng(33)
    b, S, h, d, W = 2, 256, 2, 64, 3
    q = jnp.asarray(rng.normal(size=(b, W, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, d, S)), jnp.float32)
    offs = jnp.asarray([17, 200], jnp.int32)
    reg = metrics.get_registry()
    metrics.set_enabled(True)
    reg.reset()
    try:
        out = dot_product_attention(q, k, v, causal=True,
                                    query_offset=offs, use_flash=True,
                                    kv_cache_layout=True)
        assert reg.counter("attention/flash_decode_ragged_verify") == 1
        assert reg.counter("attention/dense") == 0
        ref = _xla_attention(q, k, v, None, True, offs, 0.0, None,
                             True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)
        # paged edition
        reg.reset()
        qp, kp, vp, pt = _paged_batch(b=2, pool=7, max_pages=2,
                                      seed=34)
        qp = jnp.concatenate([qp] * W, axis=1)
        outp = dot_product_attention(qp, kp, vp, causal=True,
                                     query_offset=offs,
                                     use_flash=True,
                                     kv_cache_layout=True,
                                     page_table=pt)
        assert reg.counter("attention/flash_decode_paged_verify") == 1
        assert reg.counter("attention/dense") == 0
        kg, vg = _gather_kv_pages(kp, pt), _gather_kv_pages(vp, pt)
        refp = _xla_attention(qp, kg, vg, None, True, offs, 0.0, None,
                              True, True, kv_cache_layout=True)
        np.testing.assert_allclose(np.asarray(outp), np.asarray(refp),
                                   atol=2e-6, rtol=2e-6)
        # a chunked-prefill-sized window stays OFF the verify kernel
        reg.reset()
        big = MAX_VERIFY_WINDOW + 1
        qb = jnp.asarray(rng.normal(size=(b, big, h, d)), jnp.float32)
        dot_product_attention(qb, k, v, causal=True,
                              query_offset=jnp.zeros((b,), jnp.int32),
                              use_flash=True, kv_cache_layout=True)
        assert reg.counter("attention/flash_decode_ragged_verify") == 0
        assert reg.counter("attention/dense") == 1
    finally:
        metrics.set_enabled(False)
        reg.reset()


def test_kernel_dropout_gate_and_fallback(monkeypatch):
    """The in-kernel dropout dispatch (PFX_FLASH_DROPOUT=1) must fall
    back to the XLA dense path on CPU (prng has no interpret
    lowering), and with the gate off behave exactly as before. The
    on-chip certification lives in scripts/validate_flash_dropout.py."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddlefleetx_tpu.ops.attention import dot_product_attention

    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 64, 2, 64)),
                           jnp.float32) for _ in range(3))
    key = jax.random.key(0)
    kw = dict(causal=True, dropout_rate=0.2, dropout_rng=key,
              deterministic=False, use_flash=True)
    monkeypatch.delenv("PFX_FLASH_DROPOUT", raising=False)
    off = dot_product_attention(q, k, v, **kw)
    monkeypatch.setenv("PFX_FLASH_DROPOUT", "1")
    on = dot_product_attention(q, k, v, **kw)
    # same platform, same rng -> the CPU fallback path is identical
    np.testing.assert_allclose(np.asarray(off), np.asarray(on),
                               rtol=1e-6)
    assert np.isfinite(np.asarray(on)).all()


def test_flash_dropout_requires_rng(monkeypatch):
    """Under interpret mode the backend check passes, so the missing-
    rng check is the one that fires — pin its message (a bare
    NotImplementedError would also come from the CPU-backend check,
    making the assertion vacuous)."""
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_attention,
    )
    monkeypatch.setenv("PFX_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 128, 2, 64)), jnp.float32)
    with pytest.raises(NotImplementedError, match="dropout_rng"):
        flash_attention(q, q, q, causal=True, dropout_rate=0.1)
    # with an rng, interpret mode RUNS the dropout kernel (a stateless
    # hash stands in for the TPU prng): finite output, and really
    # dropping — it must differ from the rate-0 result
    import jax
    out = flash_attention(q, q, q, causal=True, dropout_rate=0.1,
                          dropout_rng=jax.random.key(0))
    base = flash_attention(q, q, q, causal=True)
    assert np.isfinite(np.asarray(out)).all()
    assert not np.allclose(np.asarray(out), np.asarray(base))


def test_flash_dropout_traces_offline():
    """The dropout custom_vjp cannot COMPILE off-TPU (Mosaic-only
    prng), but it must TRACE: jax.eval_shape exercises kernel ref
    counts, scalar-prefetch index-map arity, grid/spec plumbing, and
    the float0 seed cotangent — catching structural regressions
    without a chip. Numerics are certified on-chip by
    scripts/validate_flash_dropout.py."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        _flash_lse_dropout, _to_bh, check_shapes,
    )

    d = 64
    seed = jnp.zeros((1,), jnp.int32)

    def run(s, rate):
        q = jnp.zeros((2, s, 4, d), jnp.float32)
        bq, bkv = check_shapes(s, s, d)

        def loss(q_, k_, v_, s_):
            o, lse = _flash_lse_dropout(
                _to_bh(q_), _to_bh(k_), _to_bh(v_), s_, d ** -0.5,
                True, bq, bkv, rate)
            return jnp.sum(o) + jnp.sum(lse)

        return jax.eval_shape(
            lambda a, b, c, s_: jax.grad(loss, argnums=(0, 1, 2))(
                a, b, c, s_), q, q, q, seed)

    # combined-backward regime (num_q == 1) and split-pair regime
    for s in (512, 2048):
        grads = run(s, 0.2)
        assert all(g.shape == (2, s, 4, d) for g in grads)


def test_kernel_dropout_gate_self_certifying(monkeypatch, tmp_path):
    """The gate is ON iff the chip-cert artifact exists (written by
    scripts/validate_flash_dropout.py on a passing live-chip run)
    AND its device_kind matches the attached TPU — certification is
    per TPU generation, and off-TPU (this CPU test platform) the
    artifact can never enable the kernel. PFX_FLASH_DROPOUT
    overrides in both directions; empty/garbage values fall through
    to the artifact; a truncated/invalid artifact is OFF."""
    import json

    import jax

    from paddlefleetx_tpu.ops import attention

    cert = tmp_path / "dropout_cert.json"
    monkeypatch.setattr(attention, "DROPOUT_CERT_PATH", str(cert))
    monkeypatch.delenv("PFX_FLASH_DROPOUT", raising=False)
    assert not attention._kernel_dropout_enabled()  # no artifact
    cert.write_text("{\"devi")  # truncated write
    assert not attention._kernel_dropout_enabled()
    cert.write_text("{}")  # no device_kind recorded
    assert not attention._kernel_dropout_enabled()
    # kind matches the attached device, but this platform is cpu —
    # still off (the kernel cannot run here at all)
    cert.write_text(json.dumps(
        {"device_kind": jax.devices()[0].device_kind}))
    assert not attention._kernel_dropout_enabled()
    cert.write_text(json.dumps({"device_kind": "TPU v5 lite"}))
    assert not attention._kernel_dropout_enabled()  # platform != tpu
    # env forces both ways regardless of artifact state
    monkeypatch.setenv("PFX_FLASH_DROPOUT", "0")
    assert not attention._kernel_dropout_enabled()
    monkeypatch.setenv("PFX_FLASH_DROPOUT", "1")
    assert attention._kernel_dropout_enabled()
    cert.unlink()
    assert attention._kernel_dropout_enabled()  # env=1 needs no file
    # unrecognized/empty env falls through to the (absent) artifact
    monkeypatch.setenv("PFX_FLASH_DROPOUT", "")
    assert not attention._kernel_dropout_enabled()


def test_kernel_dropout_gate_matches_tpu_device(monkeypatch,
                                                tmp_path):
    """On a TPU whose device_kind matches the artifact the gate is
    on; on a different TPU generation it stays off (simulated — the
    test platform is CPU, so jax.devices is stubbed)."""
    import json

    from paddlefleetx_tpu.ops import attention

    class _Dev:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    cert = tmp_path / "dropout_cert.json"
    monkeypatch.setattr(attention, "DROPOUT_CERT_PATH", str(cert))
    monkeypatch.delenv("PFX_FLASH_DROPOUT", raising=False)
    monkeypatch.setattr(attention.jax, "devices", lambda: [_Dev()])
    cert.write_text(json.dumps({"device_kind": "TPU v5 lite"}))
    assert attention._kernel_dropout_enabled()
    cert.write_text(json.dumps({"device_kind": "TPU v4"}))
    assert not attention._kernel_dropout_enabled()


# -- additive bias on the fused path ------------------------------------

def _bias_of(shape, seed=7):
    rng = np.random.default_rng(seed)
    # mix smooth values with -1e9 padding-style entries so the test
    # covers both relative-position bias and hard masks
    b = rng.normal(size=shape).astype(np.float32)
    b[..., -5:] = -1e9
    return jnp.asarray(b)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("bias_shape", [
    (2, 2, 256, 256),   # full per-head bias (GPT attn_mask)
    (2, 1, 1, 256),     # ERNIE padding mask, broadcast over h and sq
    (1, 1, 256, 256),   # shared relative-position bias
])
def test_bias_forward_and_grads_match_xla(causal, bias_shape):
    q, k, v = _rand(b=2, s=256)
    bias = _bias_of(bias_shape)
    ref = _xla_attention(q, k, v, bias, causal, 0, 0.0, None, True,
                         True)
    got = flash_attention(q, k, v, causal=causal, bias=bias,
                          block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, bias=bias,
                                block_q=128, block_kv=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (_xla_attention(q, k, v, bias, causal, 0, 0.0, None,
                               True, True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)


def test_bias_with_dropout_matches_dropout_only_at_zero_bias():
    """The bias+dropout path folds the SAME in-kernel keep masks as
    the dropout-only path (the seed fold ignores the bias operand), so
    a zero bias must reproduce dropout-only bit-for-bit — and a real
    bias must still produce finite grads through the combined path."""
    q, k, v = _rand(b=2, s=256, seed=3)
    key = jax.random.key(5)
    kw = dict(causal=True, dropout_rate=0.2, dropout_rng=key,
              block_q=128, block_kv=128)
    plain = flash_attention(q, k, v, **kw)
    zeroed = flash_attention(q, k, v,
                             bias=jnp.zeros((2, 1, 1, 256)), **kw)
    np.testing.assert_array_equal(np.asarray(plain),
                                  np.asarray(zeroed))

    bias = _bias_of((2, 1, 1, 256))

    def loss(q, k, v):
        return (flash_attention(q, k, v, bias=bias, **kw) ** 2).sum()

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
    # dropout really fires on the biased path
    nodrop = flash_attention(q, k, v, causal=True, bias=bias,
                             block_q=128, block_kv=128)
    withdrop = flash_attention(q, k, v, bias=bias, **kw)
    assert not np.allclose(np.asarray(nodrop), np.asarray(withdrop))


def test_unsupported_bias_shape_falls_back():
    """Shapes the kernel cannot tile (non-4D, partial broadcast on the
    key axis) raise NotImplementedError from the kernel wrapper, and
    dot_product_attention silently lands on the XLA path with correct
    numerics."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    q, k, v = _rand(b=2, s=256)
    for bad in (jnp.zeros((2, 256, 256)),        # 3D
                jnp.zeros((2, 2, 256, 1))):      # broadcast key axis
        with pytest.raises(NotImplementedError, match="bias"):
            flash_attention(q, k, v, bias=bad)
    bias3 = jnp.zeros((2, 256, 256))
    out = dot_product_attention(q, k, v, bias=bias3, causal=True,
                                use_flash=True)
    ref = _xla_attention(q, k, v, bias3, True, 0, 0.0, None, True,
                         True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def test_training_bias_dropout_dispatches_to_kernel(monkeypatch):
    """ISSUE acceptance probe: with a non-None bias AND
    dropout_rate > 0 (the ERNIE/GPT masked-training shape),
    dot_product_attention(use_flash=True) must dispatch to the Pallas
    kernel, not the dense fallback."""
    from paddlefleetx_tpu.ops import attention
    from paddlefleetx_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setenv("PFX_FLASH_DROPOUT", "1")
    calls = []
    real = fa.flash_attention

    def probe(*a, **kw):
        calls.append(kw)
        return real(*a, **kw)

    monkeypatch.setattr(fa, "flash_attention", probe)
    q, k, v = _rand(b=2, s=256)
    bias = _bias_of((2, 1, 1, 256))
    out = attention.dot_product_attention(
        q, k, v, bias=bias, causal=True, dropout_rate=0.1,
        dropout_rng=jax.random.key(0), deterministic=False,
        use_flash=True)
    assert calls, "dispatch skipped the Pallas kernel"
    assert calls[-1]["bias"] is bias
    assert calls[-1]["dropout_rate"] == 0.1
    assert np.isfinite(np.asarray(out)).all()
    # deterministic (eval) with bias also stays on the kernel,
    # causal or not
    calls.clear()
    attention.dot_product_attention(q, k, v, bias=bias, causal=True,
                                    use_flash=True)
    assert calls and calls[-1]["bias"] is bias
