"""Flash-attention kernel semantics, validated on CPU via the Pallas
interpreter (the real-TPU path is exercised by bench.py and the
on-device verification runs)."""

import os

os.environ["PFX_PALLAS_INTERPRET"] = "1"

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.ops.attention import _xla_attention
from paddlefleetx_tpu.ops.pallas.flash_attention import flash_attention


def _rand(b=1, s=256, h=2, d=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_xla(causal):
    q, k, v = _rand()
    ref = _xla_attention(q, k, v, None, causal, 0, 0.0, None, True, True)
    got = flash_attention(q, k, v, causal=causal, block_q=128,
                          block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_grads_match_xla():
    q, k, v = _rand(s=256)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=128,
                                block_kv=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (_xla_attention(q, k, v, None, True, 0, 0.0, None, True,
                               True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_uneven_blocks_fall_back():
    q, k, v = _rand(s=100)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, block_q=64, block_kv=64)


def test_dispatch_falls_back_to_xla_on_unsupported():
    """ops.dot_product_attention must not crash when flash refuses."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    q, k, v = _rand(s=100)
    out = dot_product_attention(q, k, v, use_flash=True)
    ref = _xla_attention(q, k, v, None, True, 0, 0.0, None, True, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6)


def test_decode_matches_xla_and_ignores_garbage():
    """flash_decode == XLA cached-decode attention, and cache contents
    past the index never leak into the output."""
    from paddlefleetx_tpu.ops.pallas.flash_attention import flash_decode
    rng = np.random.default_rng(3)
    b, S, h, d = 2, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    # heads-first cache layout [b, h, S, d]
    k = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)
    for off in (0, 5, 130, 255):
        ref = _xla_attention(q, k, v, None, True, off, 0.0, None, True,
                             True, kv_heads_first=True)
        got = flash_decode(q, k, v, jnp.int32(off), block_kv=128)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)
        # garbage independence: mutate the cache beyond the offset
        k2 = k.at[:, :, off + 1:].set(1e3)
        v2 = v.at[:, :, off + 1:].set(-1e3)
        got2 = flash_decode(q, k2, v2, jnp.int32(off), block_kv=128)
        np.testing.assert_allclose(np.asarray(got2), np.asarray(got),
                                   atol=2e-6, rtol=2e-6)


def test_decode_works_under_jit_with_traced_offset():
    from paddlefleetx_tpu.ops.pallas.flash_attention import flash_decode
    rng = np.random.default_rng(4)
    b, S, h, d = 1, 128, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)

    @jax.jit
    def step(off):
        return flash_decode(q, k, v, off)

    a = step(jnp.int32(7))
    bb = step(jnp.int32(100))          # same trace, new offset
    ref_a = _xla_attention(q, k, v, None, True, 7, 0.0, None, True, True,
                           kv_heads_first=True)
    ref_b = _xla_attention(q, k, v, None, True, 100, 0.0, None, True,
                           True, kv_heads_first=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(ref_a),
                               atol=2e-6, rtol=2e-6)
    np.testing.assert_allclose(np.asarray(bb), np.asarray(ref_b),
                               atol=2e-6, rtol=2e-6)


def test_decode_dispatch_from_dot_product_attention():
    """dot_product_attention routes single-token cached decode to the
    kernel (use_flash) and falls back cleanly on odd shapes."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    rng = np.random.default_rng(5)
    b, S, h, d = 1, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)
    out = dot_product_attention(q, k, v, causal=True,
                                query_offset=jnp.int32(17),
                                use_flash=True, kv_heads_first=True)
    ref = _xla_attention(q, k, v, None, True, 17, 0.0, None, True, True,
                         kv_heads_first=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
    # head_dim the kernel rejects -> XLA fallback, still correct
    q2 = q[..., :48]; k2 = k[..., :48]; v2 = v[..., :48]
    out2 = dot_product_attention(q2, k2, v2, causal=True,
                                 query_offset=jnp.int32(3),
                                 use_flash=True, kv_heads_first=True)
    ref2 = _xla_attention(q2, k2, v2, None, True, 3, 0.0, None, True,
                          True, kv_heads_first=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               atol=2e-6, rtol=2e-6)


def test_decode_with_leftpad_bias_matches_xla():
    """The decode kernel honors the generation loop's [b,1,1,S]
    additive left-pad bias."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    rng = np.random.default_rng(6)
    b, S, h, d = 2, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, S, d)), jnp.float32)
    # row 0 pads the first 3 slots, row 1 the first 120
    valid = np.ones((b, S), bool)
    valid[0, :3] = False
    valid[1, :120] = False
    bias = jnp.where(jnp.asarray(valid), 0.0, -1e9)[:, None, None, :]
    off = jnp.int32(130)
    out = dot_product_attention(q, k, v, bias=bias, causal=True,
                                query_offset=off, use_flash=True,
                                kv_heads_first=True)
    ref = _xla_attention(q, k, v, bias, True, off, 0.0, None, True, True,
                         kv_heads_first=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)
