"""Flash-attention kernel semantics, validated on CPU via the Pallas
interpreter (the real-TPU path is exercised by bench.py and the
on-device verification runs)."""

import os

os.environ["PFX_PALLAS_INTERPRET"] = "1"

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.ops.attention import _xla_attention
from paddlefleetx_tpu.ops.pallas.flash_attention import flash_attention


def _rand(b=1, s=256, h=2, d=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_xla(causal):
    q, k, v = _rand()
    ref = _xla_attention(q, k, v, None, causal, 0, 0.0, None, True, True)
    got = flash_attention(q, k, v, causal=causal, block_q=128,
                          block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_grads_match_xla():
    q, k, v = _rand(s=256)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=128,
                                block_kv=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (_xla_attention(q, k, v, None, True, 0, 0.0, None, True,
                               True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_uneven_blocks_fall_back():
    q, k, v = _rand(s=100)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, block_q=64, block_kv=64)


def test_dispatch_falls_back_to_xla_on_unsupported():
    """ops.dot_product_attention must not crash when flash refuses."""
    from paddlefleetx_tpu.ops.attention import dot_product_attention
    q, k, v = _rand(s=100)
    out = dot_product_attention(q, k, v, use_flash=True)
    ref = _xla_attention(q, k, v, None, True, 0, 0.0, None, True, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6)
