"""Pipeline parallelism: pipelined == sequential, on the CPU mesh.

The reference can only validate PP by running 1F1B on a GPU pod
(SURVEY.md §4); here the SPMD pipeline (``parallel/pipeline.py``) is
checked for exact agreement with the unpipelined model, including
composites with TP and DP, and gradient equality.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.models.gpt import (
    GPTConfig, GPTForPretraining, cross_entropy_loss,
)
from paddlefleetx_tpu.models.gpt.model import (
    pipelined_lm_loss, pipelined_lm_loss_and_grad,
)
from paddlefleetx_tpu.parallel import (
    TopologyConfig, build_mesh, make_sharding_rules,
)
from paddlefleetx_tpu.parallel.mesh import set_mesh
from paddlefleetx_tpu.parallel.pipeline import (
    pipeline_forward, pipeline_value_and_grad,
)

CFG = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                num_attention_heads=4, max_position_embeddings=32,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


def test_pipeline_forward_plain_math():
    """No mesh, no flax: pipeline over scalar-scale 'layers' equals
    sequential application, microbatch-exact."""
    L, B = 4, 6
    w = jnp.arange(1.0, L + 1)[:, None]          # stacked [L, 1]
    x = jnp.arange(B, dtype=jnp.float32)[:, None] + 1.0

    def layer_apply(lp, h, key):
        return h * lp[0] + 1.0

    out = pipeline_forward(layer_apply, w, x, pp=2, num_microbatches=3)
    ref = x
    for i in range(L):
        ref = ref * w[i, 0] + 1.0
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6)


def test_pipeline_forward_reducer():
    """out_fn accumulates per-microbatch results with extras."""
    L, B = 2, 4
    w = jnp.ones((L, 1))
    x = jnp.arange(B, dtype=jnp.float32)[:, None]
    extras = 10.0 * jnp.ones((B, 1))

    def layer_apply(lp, h, key):
        return h + lp[0]

    def out_fn(acc, y, ex):
        return acc + jnp.sum(y) + jnp.sum(ex)

    out = pipeline_forward(layer_apply, w, x, pp=2, num_microbatches=2,
                           out_fn=out_fn, out_init=jnp.zeros(()),
                           extras=extras)
    # sequential: each row gains +2; sum(x)+2*B + sum(extras)
    np.testing.assert_allclose(float(out),
                               float(jnp.sum(x) + 2 * B + 40.0))


@pytest.mark.parametrize("vpp", [1, 2])
def test_pipeline_forward_vpp_plain_math(vpp):
    """Interleaved virtual stages: pp=2, vpp-way chunking over L=8
    'layers' equals sequential application."""
    L, B = 8, 6
    w = jnp.arange(1.0, L + 1)[:, None] / L      # stacked [L, 1]
    x = jnp.arange(B, dtype=jnp.float32)[:, None] + 1.0

    def layer_apply(lp, h, key):
        return h * lp[0] + 0.5

    out = pipeline_forward(layer_apply, w, x, pp=2, num_microbatches=3,
                           vpp=vpp)
    ref = x
    for i in range(L):
        ref = ref * w[i, 0] + 0.5
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6)


@pytest.mark.parametrize("vpp, M", [(1, 4), (2, 4), (2, 1), (1, 7)])
def test_pipeline_value_and_grad_plain_math(vpp, M):
    """The explicit 1F1B schedule returns the same loss and gradients
    as autodiff through sequential layer application."""
    L, B = 8, 28
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(L, 3)), jnp.float32) * 0.3
    x = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(3,)), jnp.float32)

    def layer_apply(lp, h, key):
        return jnp.tanh(h * lp[None, :] + 0.1)

    ref_loss, (ref_dw, ref_dbias) = jax.value_and_grad(
        lambda p: _seq_loss_on(x, p[0], p[1], tgt, layer_apply,
                               M))((w, bias))

    def loss_and_grad(y, ex):
        def head(b_, yy):
            return jnp.mean(jnp.sum((yy + b_ - ex) ** 2, -1))
        l, pull = jax.vjp(head, bias, y)
        db, dy = pull(jnp.ones((), jnp.float32))
        return l, dy, db

    loss_sum, dw, dbias, dx = pipeline_value_and_grad(
        layer_apply, w, x, pp=2, num_microbatches=M, vpp=vpp,
        loss_and_grad=loss_and_grad, extras=tgt)
    np.testing.assert_allclose(float(loss_sum) / M, float(ref_loss),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dw) / M, np.asarray(ref_dw),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dbias) / M,
                               np.asarray(ref_dbias),
                               atol=1e-5, rtol=1e-4)
    # dx agrees with autodiff wrt the input
    ref_dx = jax.grad(
        lambda xx: _seq_loss_on(xx, w, bias, tgt, layer_apply, M))(x)
    np.testing.assert_allclose(np.asarray(dx) / M, np.asarray(ref_dx),
                               atol=1e-5, rtol=1e-4)


def _seq_loss_on(x, w, bias, tgt, layer_apply, M):
    h = x
    for i in range(w.shape[0]):
        h = layer_apply(w[i], h, None)
    hm = (h + bias).reshape(M, x.shape[0] // M, -1)
    tm = tgt.reshape(M, x.shape[0] // M, -1)
    return jnp.mean(jnp.sum((hm - tm) ** 2, -1), axis=-1).mean()


def _data(batch=8, seq=16):
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 64, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 64, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.float32)
    return ids, labels, mask


@pytest.fixture(scope="module")
def golden():
    variables = GPTForPretraining(CFG).init(
        {"params": jax.random.key(0)}, jnp.zeros((1, 8), jnp.int32))
    ids, labels, mask = _data()
    model = GPTForPretraining(CFG)

    def f(params):
        logits = model.apply({"params": params}, ids)
        return cross_entropy_loss(logits, labels, mask)

    params = nn.meta.unbox(variables)["params"]
    loss, grads = jax.value_and_grad(f)(params)
    return params, ids, labels, mask, loss, grads


@pytest.mark.parametrize("topo_kw, microbatches, vpp", [
    ({"pp_degree": 2}, 4, 1),
    ({"pp_degree": 4, "dp_degree": 2}, 2, 1),
    ({"pp_degree": 2, "mp_degree": 2, "dp_degree": 2}, 4, 1),
    # the dryrun_multichip composite as a pytest case: TP inside a
    # stage + ZeRO-3 param sharding + pipeline, all at once
    ({"pp_degree": 2, "mp_degree": 2, "sharding_degree": 2,
      "sharding_stage": 3}, 2, 1),
    ({"pp_degree": 2}, 1, 1),
    # interleaved virtual stages: physical stage s owns layer chunks
    # {s, s+2} of L=4 (reference virtual_pp_degree semantics)
    ({"pp_degree": 2}, 4, 2),
    ({"pp_degree": 2, "mp_degree": 2, "dp_degree": 2}, 4, 2),
], ids=["pp2", "pp4xdp2", "pp2xmp2xdp2", "pp2xmp2xfsdp2", "pp2-m1",
        "pp2-vpp2", "pp2xmp2xdp2-vpp2"])
def test_pipelined_matches_single_device(golden, topo_kw, microbatches,
                                         vpp):
    params, ids, labels, mask, ref_loss, ref_grads = golden
    topo = TopologyConfig(**topo_kw)
    devices = jax.devices()[:topo.world_size]
    mesh = build_mesh(topo, devices=devices)
    set_mesh(mesh)
    rules = make_sharding_rules(topo)

    model = GPTForPretraining(CFG)
    logical_specs = nn.get_partition_spec(
        jax.eval_shape(model.init, {"params": jax.random.key(0)},
                       jnp.zeros((1, 8), jnp.int32)))
    shardings = nn.logical_to_mesh_sharding(logical_specs, mesh,
                                            list(rules))
    params_s = jax.device_put({"params": params},
                              nn.meta.unbox(shardings))["params"]
    data_sharding = NamedSharding(mesh, P(("dp", "fsdp"), None))
    ids_s, labels_s, mask_s = (jax.device_put(x, data_sharding)
                               for x in (ids, labels, mask))

    def f(p, i, l, m):
        return pipelined_lm_loss(
            CFG, p, i, l, m, pp=topo.pp_degree,
            num_microbatches=microbatches, vpp=vpp, deterministic=True)

    def f_1f1b(p, i, l, m):
        return pipelined_lm_loss_and_grad(
            CFG, p, i, l, m, pp=topo.pp_degree,
            num_microbatches=microbatches, vpp=vpp, deterministic=True)

    with mesh, nn.logical_axis_rules(list(rules)):
        loss, grads = jax.jit(jax.value_and_grad(f))(
            params_s, ids_s, labels_s, mask_s)
        loss2, grads2 = jax.jit(f_1f1b)(params_s, ids_s, labels_s,
                                        mask_s)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3),
        ref_grads, grads)
    # the explicit 1F1B schedule computes the identical loss/grads
    np.testing.assert_allclose(float(loss2), float(ref_loss), rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3),
        ref_grads, grads2)


def test_pipelined_loss_weighting_matches_accumulation(golden):
    """With masks that vary across microbatches, the pp loss equals the
    engine accumulation semantics: mean over microbatches of the
    per-microbatch masked mean (reference 1F1B micro-loss averaging)."""
    params, ids, labels, _, _, _ = golden
    rng = np.random.default_rng(3)
    mask = jnp.asarray((rng.random(ids.shape) > 0.4), jnp.float32)
    M = 4
    model = GPTForPretraining(CFG)
    per_mb = []
    for i in range(M):
        sl = slice(i * ids.shape[0] // M, (i + 1) * ids.shape[0] // M)
        logits = model.apply({"params": params}, ids[sl])
        per_mb.append(cross_entropy_loss(logits, labels[sl], mask[sl]))
    want = float(np.mean([float(x) for x in per_mb]))

    topo = TopologyConfig(pp_degree=2)
    mesh = build_mesh(topo, devices=jax.devices()[:2])
    set_mesh(mesh)
    rules = make_sharding_rules(topo)
    with mesh, nn.logical_axis_rules(list(rules)):
        got = jax.jit(lambda p: pipelined_lm_loss(
            CFG, p, ids, labels, mask, pp=2, num_microbatches=M,
            deterministic=True))(params)
    np.testing.assert_allclose(float(got), want, rtol=2e-5)


def test_1f1b_uses_less_activation_memory_than_gpipe():
    """The 1F1B property: with many microbatches the explicit schedule's
    temp (activation) memory is bounded by pipeline depth, while
    autodiff through the GPipe forward stashes every microbatch —
    XLA's own memory analysis shows the gap (the reference's reason
    for defaulting to 1F1B)."""
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_attention_heads=4, max_position_embeddings=64,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    params = nn.meta.unbox(GPTForPretraining(cfg).init(
        {"params": jax.random.key(0)},
        jnp.zeros((1, 8), jnp.int32)))["params"]
    B, S, M = 32, 32, 16
    ids = jnp.zeros((B, S), jnp.int32)
    mask = jnp.ones((B, S), jnp.float32)

    gpipe = jax.jit(jax.value_and_grad(lambda p: pipelined_lm_loss(
        cfg, p, ids, ids, mask, pp=1, num_microbatches=M,
        deterministic=True)))
    f1b = jax.jit(lambda p: pipelined_lm_loss_and_grad(
        cfg, p, ids, ids, mask, pp=1, num_microbatches=M,
        deterministic=True))
    mems = {}
    for name, fn in (("gpipe", gpipe), ("1f1b", f1b)):
        ma = fn.lower(params).compile().memory_analysis()
        if ma is None:
            pytest.skip("backend provides no memory analysis")
        mems[name] = ma.temp_size_in_bytes
    assert mems["1f1b"] < 0.8 * mems["gpipe"], mems


def test_decoder_params_sharded_over_pp():
    topo = TopologyConfig(pp_degree=2, mp_degree=2, dp_degree=2)
    mesh = build_mesh(topo)
    rules = make_sharding_rules(topo)
    logical_specs = nn.get_partition_spec(
        jax.eval_shape(GPTForPretraining(CFG).init,
                       {"params": jax.random.key(0)},
                       jnp.zeros((1, 8), jnp.int32)))
    shardings = nn.logical_to_mesh_sharding(logical_specs, mesh,
                                            list(rules))
    qkv = shardings["params"]["gpt"]["decoder"]["self_attn"][
        "qkv_proj"]["kernel"]
    assert qkv.spec == P("pp", None, None, "mp", None)


# -- zero-bubble schedule ----------------------------------------------

from paddlefleetx_tpu.parallel.pipeline import (  # noqa: E402
    _slot_keys, pipeline_tick_stats, zb_dw_schedule, zb_queue_bound,
)


def _dropout_layer(lp, h, key):
    """Plain-math layer WITH dropout: the parity matrix below pins the
    (microbatch, virtual stage) key-fold contract — both schedules and
    the sequential reference must draw identical masks."""
    y = jnp.tanh(h * lp[None, :] + 0.1)
    keep = jax.random.bernoulli(key, 0.8, y.shape)
    return jnp.where(keep, y / 0.8, 0.0)


def _zb_ref_loss(x, wb, tgt, base_rng, K, M):
    """Sequential reference replaying the pipeline's exact dropout
    keys: fold (m, k) via _slot_keys, split Lc layer keys per slot."""
    w, bias = wb
    Lc = w.shape[0] // K
    xs = x.reshape(M, x.shape[0] // M, -1)
    ts = tgt.reshape(M, tgt.shape[0] // M, -1)
    total = jnp.zeros((), jnp.float32)
    for m in range(M):
        h = xs[m]
        keys = _slot_keys(base_rng, jnp.full((K,), m), K)
        for k in range(K):
            lkeys = jax.random.split(keys[k], Lc)
            for j in range(Lc):
                h = _dropout_layer(w[k * Lc + j], h, lkeys[j])
        total = total + jnp.mean(jnp.sum((h + bias - ts[m]) ** 2, -1))
    return total


@pytest.mark.parametrize("pp", [2, 4])
@pytest.mark.parametrize("vpp", [1, 2])
@pytest.mark.parametrize("M", [4, 8])
def test_zb_grad_parity_matrix(pp, vpp, M):
    """zb == 1f1b == sequential reference (loss, dparams, dx) with
    dropout ON across the pp x vpp x M matrix. dparams/dx are
    bit-identical between the schedules (the dW FIFO drains in
    microbatch order, so even the fp32 accumulation order matches);
    the reference is matched to tolerance."""
    L, B = 8, 24
    rng = np.random.default_rng(11)
    w = jnp.asarray(rng.normal(size=(L, 3)), jnp.float32) * 0.3
    x = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(3,)), jnp.float32)
    base_rng = jax.random.key(42)

    def loss_and_grad(y, ex):
        def head(b_, yy):
            return jnp.mean(jnp.sum((yy + b_ - ex) ** 2, -1))
        l, pull = jax.vjp(head, bias, y)
        db, dy = pull(jnp.ones((), jnp.float32))
        return l, dy, db

    out = {}
    for sched in ("1f1b", "zb", "zb_h2"):
        out[sched] = pipeline_value_and_grad(
            _dropout_layer, w, x, pp=pp, num_microbatches=M, vpp=vpp,
            loss_and_grad=loss_and_grad, extras=tgt, rng=base_rng,
            schedule=sched)
    l1, dw1, db1, dx1 = out["1f1b"]
    for sched in ("zb", "zb_h2"):
        l2, dw2, db2, dx2 = out[sched]
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(dw1), np.asarray(dw2))
        np.testing.assert_allclose(np.asarray(db1), np.asarray(db2),
                                   rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(dx1), np.asarray(dx2))
    l2, dw2, db2, dx2 = out["zb"]

    K = pp * vpp
    ref_loss, (ref_dw, ref_db) = jax.value_and_grad(
        lambda p: _zb_ref_loss(x, p, tgt, base_rng, K, M))((w, bias))
    ref_dx = jax.grad(
        lambda xx: _zb_ref_loss(xx, (w, bias), tgt, base_rng, K, M))(x)
    np.testing.assert_allclose(float(l2), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dw2), np.asarray(ref_dw),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(db2), np.asarray(ref_db),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dx2), np.asarray(ref_dx),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("h2_depth", [0, 1, 3])
@pytest.mark.parametrize("M, K", [(1, 2), (4, 2), (3, 4), (4, 4),
                                  (8, 4), (8, 8), (16, 4)])
def test_zb_dw_schedule_bounds(M, K, h2_depth):
    """The dW timetable drains every (microbatch, slot) job exactly
    once, in microbatch order, never before its dX tick, never after
    the tick-``m + 2K - 1`` bound that keeps the activation ring at
    depth 2K, and the FIFO depth stays within the documented bound —
    at every H2 depth."""
    dw, max_depth = zb_dw_schedule(M, K, h2_depth=h2_depth)
    assert dw.shape == (M + 2 * K - 1, K)
    assert max_depth <= zb_queue_bound(M, K, h2_depth=h2_depth)
    for k in range(K):
        drained = [int(m) for m in dw[:, k] if m >= 0]
        assert drained == list(range(M))   # exactly once, FIFO order
        for t in range(dw.shape[0]):
            if dw[t, k] >= 0:
                assert t >= int(dw[t, k]) + 2 * K - 1 - k
                assert t <= int(dw[t, k]) + 2 * K - 1


def test_zb_dw_schedule_depth0_is_zb():
    """h2_depth=0 reproduces the plain-zb timetable bit for bit (the
    just-in-time pop rule fires exactly when the overflow rule does)."""
    for M, K in [(1, 2), (4, 2), (8, 4), (7, 4), (16, 8)]:
        a, da = zb_dw_schedule(M, K)
        b, db = zb_dw_schedule(M, K, h2_depth=0)
        np.testing.assert_array_equal(a, b)
        assert da == db


def test_zb_tick_stats_fill_half_bubble():
    """Acceptance shape (pp4, M=8) under the decoupled-stage unit
    model: zb's deferred-dW drain halves the 1f1b bubble, and zb_h2 at
    full depth (M >= 2K - 1) eliminates it."""
    a = pipeline_tick_stats(8, 4, schedule="1f1b")
    b = pipeline_tick_stats(8, 4, schedule="zb")
    h = pipeline_tick_stats(8, 4, schedule="zb_h2")
    assert a["fwd_ticks"] == b["fwd_ticks"] == h["fwd_ticks"] == 32
    assert a["bwd_dx_ticks"] == b["bwd_dx_ticks"] == 32
    assert a["bwd_dw_ticks"] == b["bwd_dw_ticks"] == 32
    # span accounting: total = 3MK work + bubble inside the spans
    assert a["total_slot_ticks"] == 108
    assert b["total_slot_ticks"] == 102
    # dW occupies >= half of the former fill/drain bubble (integer
    # math; at M >= 2K-1 it is exactly half — K(K-1)/2)
    assert 2 * (a["bubble_ticks"] - b["bubble_ticks"]) >= \
        a["bubble_ticks"], (a, b)
    assert a["bubble_ticks"] == 12 and b["bubble_ticks"] == 6
    # zb_h2 at full depth d = K-1: zero bubble, makespan 3M + K - 1
    assert h["h2_depth"] == 3
    assert h["bubble_ticks"] == 0
    assert h["total_slot_ticks"] == 96
    assert h["makespan_ticks"] == 27
    # intermediate depth: (K-1-d)(K-d)/2
    assert pipeline_tick_stats(8, 4, schedule="zb_h2",
                               h2_depth=1)["bubble_ticks"] == 3


@pytest.mark.parametrize("M, K", [(1, 2), (2, 2), (4, 2), (3, 4),
                                  (4, 4), (7, 4), (8, 4), (16, 4),
                                  (8, 8), (15, 8)])
def test_tick_stats_conservation_and_monotonicity(M, K):
    """Property grid: for every schedule the slot-tick split conserves
    (fwd + bwd_dx + bwd_dw + bubble == total_slot_ticks), the bubble
    is monotonically non-increasing along 1f1b -> zb -> zb_h2 (and in
    H2 depth), strictly decreasing zb -> zb_h2 at M >= K (except
    (M=2, K=2), where zb is already bubble-optimal), zero at full
    depth once M >= 2K - 1 — and no replayed dW timetable ever
    exceeds ``zb_queue_bound``."""
    stats = {}
    for sched in ("gpipe", "1f1b", "zb", "zb_h2"):
        ts = pipeline_tick_stats(M, K, schedule=sched)
        assert ts["fwd_ticks"] + ts["bwd_dx_ticks"] + \
            ts["bwd_dw_ticks"] + ts["bubble_ticks"] == \
            ts["total_slot_ticks"], (sched, ts)
        stats[sched] = ts
    assert stats["1f1b"]["bubble_ticks"] >= stats["zb"]["bubble_ticks"]
    assert stats["zb"]["bubble_ticks"] >= stats["zb_h2"]["bubble_ticks"]
    if M >= K and (M, K) != (2, 2):
        assert stats["zb_h2"]["bubble_ticks"] < \
            stats["zb"]["bubble_ticks"]
    if M >= 2 * K - 1:
        assert stats["1f1b"]["bubble_ticks"] == K * (K - 1)
        assert stats["zb"]["bubble_ticks"] == K * (K - 1) // 2
        assert stats["zb_h2"]["bubble_ticks"] == 0
    prev = None
    for d in range(K):
        ts = pipeline_tick_stats(M, K, schedule="zb_h2", h2_depth=d)
        assert ts["fwd_ticks"] + ts["bwd_dx_ticks"] + \
            ts["bwd_dw_ticks"] + ts["bubble_ticks"] == \
            ts["total_slot_ticks"]
        if M >= 2 * K - 1:
            assert ts["bubble_ticks"] == (K - 1 - d) * (K - d) // 2
        if prev is not None:
            assert ts["bubble_ticks"] <= prev
        prev = ts["bubble_ticks"]
        # the scan-side timetable honors the documented queue bound
        _, max_depth = zb_dw_schedule(M, K, h2_depth=d)
        assert max_depth <= zb_queue_bound(M, K, h2_depth=d)
        # the unit model defers at most one dW per microbatch
        assert ts["dw_queue_peak"] <= M


@pytest.fixture
def _registry():
    from paddlefleetx_tpu.observability import metrics as obs_metrics
    reg = obs_metrics.get_registry()
    prior = reg.enabled
    reg.reset()
    obs_metrics.set_enabled(True)
    yield reg
    obs_metrics.set_enabled(prior)
    reg.reset()


def test_pipeline_tick_counters(_registry):
    """The pipeline/* counter family records the scheduled tick trace
    at trace time; the zb-vs-1f1b bubble halving is asserted from the
    counters themselves (acceptance), not the analytic helper."""
    L, B, M, pp = 8, 16, 8, 4
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(L, 3)), jnp.float32) * 0.3
    x = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(3,)), jnp.float32)

    def loss_and_grad(y, ex):
        def head(b_, yy):
            return jnp.mean(jnp.sum((yy + b_ - ex) ** 2, -1))
        l, pull = jax.vjp(head, bias, y)
        db, dy = pull(jnp.ones((), jnp.float32))
        return l, dy, db

    bubbles = {}
    for sched in ("1f1b", "zb", "zb_h2"):
        _registry.reset()
        pipeline_value_and_grad(
            _dropout_layer, w, x, pp=pp, num_microbatches=M,
            loss_and_grad=loss_and_grad, extras=tgt, schedule=sched)
        assert _registry.counter("pipeline/fwd_ticks") == M * pp
        assert _registry.counter("pipeline/bwd_dx_ticks") == M * pp
        assert _registry.counter("pipeline/bwd_dw_ticks") == M * pp
        bubbles[sched] = _registry.counter("pipeline/bubble_ticks")
        if sched == "zb_h2":
            # full depth K-1 recorded; M=8 >= 2K-1 -> zero bubble
            assert _registry.counter("pipeline/h2_depth") == pp - 1
            assert bubbles[sched] == 0
    assert 2 * (bubbles["1f1b"] - bubbles["zb"]) >= bubbles["1f1b"], \
        bubbles
    assert bubbles["zb_h2"] < bubbles["zb"]


@pytest.mark.parametrize("topo_kw, microbatches, vpp", [
    ({"pp_degree": 2}, 4, 1),
    ({"pp_degree": 2, "mp_degree": 2, "dp_degree": 2}, 4, 2),
], ids=["zb-pp2", "zb-pp2xmp2xdp2-vpp2"])
def test_pipelined_zb_matches_single_device(golden, topo_kw,
                                            microbatches, vpp):
    """The full GPT model under schedule zb on a real pp mesh matches
    the non-pipelined single-device loss/grads (CI parity smoke)."""
    params, ids, labels, mask, ref_loss, ref_grads = golden
    topo = TopologyConfig(**topo_kw)
    mesh = build_mesh(topo, devices=jax.devices()[:topo.world_size])
    set_mesh(mesh)
    rules = make_sharding_rules(topo)

    def f_zb(p, i, l, m):
        return pipelined_lm_loss_and_grad(
            CFG, p, i, l, m, pp=topo.pp_degree,
            num_microbatches=microbatches, vpp=vpp,
            deterministic=True, schedule="zb")

    with mesh, nn.logical_axis_rules(list(rules)):
        loss, grads = jax.jit(f_zb)(params, ids, labels, mask)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3),
        ref_grads, grads)


@pytest.mark.parametrize("topo_kw, microbatches, vpp", [
    ({"pp_degree": 2}, 4, 1),
    ({"pp_degree": 2, "mp_degree": 2, "dp_degree": 2}, 4, 2),
], ids=["h2-pp2", "h2-pp2xmp2xdp2-vpp2"])
def test_pipelined_h2_matches_single_device(golden, topo_kw,
                                            microbatches, vpp):
    """The full GPT model under schedule zb_h2 (full depth) on a real
    pp mesh matches the non-pipelined single-device loss/grads (the CI
    zb_h2 parity smoke)."""
    params, ids, labels, mask, ref_loss, ref_grads = golden
    topo = TopologyConfig(**topo_kw)
    mesh = build_mesh(topo, devices=jax.devices()[:topo.world_size])
    set_mesh(mesh)
    rules = make_sharding_rules(topo)

    def f_h2(p, i, l, m):
        return pipelined_lm_loss_and_grad(
            CFG, p, i, l, m, pp=topo.pp_degree,
            num_microbatches=microbatches, vpp=vpp,
            deterministic=True, schedule="zb_h2", h2_depth=-1)

    with mesh, nn.logical_axis_rules(list(rules)):
        loss, grads = jax.jit(f_h2)(params, ids, labels, mask)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3),
        ref_grads, grads)
