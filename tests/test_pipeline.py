"""Pipeline parallelism: pipelined == sequential, on the CPU mesh.

The reference can only validate PP by running 1F1B on a GPU pod
(SURVEY.md §4); here the SPMD pipeline (``parallel/pipeline.py``) is
checked for exact agreement with the unpipelined model, including
composites with TP and DP, and gradient equality.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.models.gpt import (
    GPTConfig, GPTForPretraining, cross_entropy_loss,
)
from paddlefleetx_tpu.models.gpt.model import (
    pipelined_lm_loss, pipelined_lm_loss_and_grad,
)
from paddlefleetx_tpu.parallel import (
    TopologyConfig, build_mesh, make_sharding_rules,
)
from paddlefleetx_tpu.parallel.mesh import set_mesh
from paddlefleetx_tpu.parallel.pipeline import (
    pipeline_forward, pipeline_value_and_grad,
)

CFG = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                num_attention_heads=4, max_position_embeddings=32,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


def test_pipeline_forward_plain_math():
    """No mesh, no flax: pipeline over scalar-scale 'layers' equals
    sequential application, microbatch-exact."""
    L, B = 4, 6
    w = jnp.arange(1.0, L + 1)[:, None]          # stacked [L, 1]
    x = jnp.arange(B, dtype=jnp.float32)[:, None] + 1.0

    def layer_apply(lp, h, key):
        return h * lp[0] + 1.0

    out = pipeline_forward(layer_apply, w, x, pp=2, num_microbatches=3)
    ref = x
    for i in range(L):
        ref = ref * w[i, 0] + 1.0
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6)


def test_pipeline_forward_reducer():
    """out_fn accumulates per-microbatch results with extras."""
    L, B = 2, 4
    w = jnp.ones((L, 1))
    x = jnp.arange(B, dtype=jnp.float32)[:, None]
    extras = 10.0 * jnp.ones((B, 1))

    def layer_apply(lp, h, key):
        return h + lp[0]

    def out_fn(acc, y, ex):
        return acc + jnp.sum(y) + jnp.sum(ex)

    out = pipeline_forward(layer_apply, w, x, pp=2, num_microbatches=2,
                           out_fn=out_fn, out_init=jnp.zeros(()),
                           extras=extras)
    # sequential: each row gains +2; sum(x)+2*B + sum(extras)
    np.testing.assert_allclose(float(out),
                               float(jnp.sum(x) + 2 * B + 40.0))


@pytest.mark.parametrize("vpp", [1, 2])
def test_pipeline_forward_vpp_plain_math(vpp):
    """Interleaved virtual stages: pp=2, vpp-way chunking over L=8
    'layers' equals sequential application."""
    L, B = 8, 6
    w = jnp.arange(1.0, L + 1)[:, None] / L      # stacked [L, 1]
    x = jnp.arange(B, dtype=jnp.float32)[:, None] + 1.0

    def layer_apply(lp, h, key):
        return h * lp[0] + 0.5

    out = pipeline_forward(layer_apply, w, x, pp=2, num_microbatches=3,
                           vpp=vpp)
    ref = x
    for i in range(L):
        ref = ref * w[i, 0] + 0.5
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6)


@pytest.mark.parametrize("vpp, M", [(1, 4), (2, 4), (2, 1), (1, 7)])
def test_pipeline_value_and_grad_plain_math(vpp, M):
    """The explicit 1F1B schedule returns the same loss and gradients
    as autodiff through sequential layer application."""
    L, B = 8, 28
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(L, 3)), jnp.float32) * 0.3
    x = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(B, 3)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(3,)), jnp.float32)

    def layer_apply(lp, h, key):
        return jnp.tanh(h * lp[None, :] + 0.1)

    ref_loss, (ref_dw, ref_dbias) = jax.value_and_grad(
        lambda p: _seq_loss_on(x, p[0], p[1], tgt, layer_apply,
                               M))((w, bias))

    def loss_and_grad(y, ex):
        def head(b_, yy):
            return jnp.mean(jnp.sum((yy + b_ - ex) ** 2, -1))
        l, pull = jax.vjp(head, bias, y)
        db, dy = pull(jnp.ones((), jnp.float32))
        return l, dy, db

    loss_sum, dw, dbias, dx = pipeline_value_and_grad(
        layer_apply, w, x, pp=2, num_microbatches=M, vpp=vpp,
        loss_and_grad=loss_and_grad, extras=tgt)
    np.testing.assert_allclose(float(loss_sum) / M, float(ref_loss),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dw) / M, np.asarray(ref_dw),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dbias) / M,
                               np.asarray(ref_dbias),
                               atol=1e-5, rtol=1e-4)
    # dx agrees with autodiff wrt the input
    ref_dx = jax.grad(
        lambda xx: _seq_loss_on(xx, w, bias, tgt, layer_apply, M))(x)
    np.testing.assert_allclose(np.asarray(dx) / M, np.asarray(ref_dx),
                               atol=1e-5, rtol=1e-4)


def _seq_loss_on(x, w, bias, tgt, layer_apply, M):
    h = x
    for i in range(w.shape[0]):
        h = layer_apply(w[i], h, None)
    hm = (h + bias).reshape(M, x.shape[0] // M, -1)
    tm = tgt.reshape(M, x.shape[0] // M, -1)
    return jnp.mean(jnp.sum((hm - tm) ** 2, -1), axis=-1).mean()


def _data(batch=8, seq=16):
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 64, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 64, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.float32)
    return ids, labels, mask


@pytest.fixture(scope="module")
def golden():
    variables = GPTForPretraining(CFG).init(
        {"params": jax.random.key(0)}, jnp.zeros((1, 8), jnp.int32))
    ids, labels, mask = _data()
    model = GPTForPretraining(CFG)

    def f(params):
        logits = model.apply({"params": params}, ids)
        return cross_entropy_loss(logits, labels, mask)

    params = nn.meta.unbox(variables)["params"]
    loss, grads = jax.value_and_grad(f)(params)
    return params, ids, labels, mask, loss, grads


@pytest.mark.parametrize("topo_kw, microbatches, vpp", [
    ({"pp_degree": 2}, 4, 1),
    ({"pp_degree": 4, "dp_degree": 2}, 2, 1),
    ({"pp_degree": 2, "mp_degree": 2, "dp_degree": 2}, 4, 1),
    # the dryrun_multichip composite as a pytest case: TP inside a
    # stage + ZeRO-3 param sharding + pipeline, all at once
    ({"pp_degree": 2, "mp_degree": 2, "sharding_degree": 2,
      "sharding_stage": 3}, 2, 1),
    ({"pp_degree": 2}, 1, 1),
    # interleaved virtual stages: physical stage s owns layer chunks
    # {s, s+2} of L=4 (reference virtual_pp_degree semantics)
    ({"pp_degree": 2}, 4, 2),
    ({"pp_degree": 2, "mp_degree": 2, "dp_degree": 2}, 4, 2),
], ids=["pp2", "pp4xdp2", "pp2xmp2xdp2", "pp2xmp2xfsdp2", "pp2-m1",
        "pp2-vpp2", "pp2xmp2xdp2-vpp2"])
def test_pipelined_matches_single_device(golden, topo_kw, microbatches,
                                         vpp):
    params, ids, labels, mask, ref_loss, ref_grads = golden
    topo = TopologyConfig(**topo_kw)
    devices = jax.devices()[:topo.world_size]
    mesh = build_mesh(topo, devices=devices)
    set_mesh(mesh)
    rules = make_sharding_rules(topo)

    model = GPTForPretraining(CFG)
    logical_specs = nn.get_partition_spec(
        jax.eval_shape(model.init, {"params": jax.random.key(0)},
                       jnp.zeros((1, 8), jnp.int32)))
    shardings = nn.logical_to_mesh_sharding(logical_specs, mesh,
                                            list(rules))
    params_s = jax.device_put({"params": params},
                              nn.meta.unbox(shardings))["params"]
    data_sharding = NamedSharding(mesh, P(("dp", "fsdp"), None))
    ids_s, labels_s, mask_s = (jax.device_put(x, data_sharding)
                               for x in (ids, labels, mask))

    def f(p, i, l, m):
        return pipelined_lm_loss(
            CFG, p, i, l, m, pp=topo.pp_degree,
            num_microbatches=microbatches, vpp=vpp, deterministic=True)

    def f_1f1b(p, i, l, m):
        return pipelined_lm_loss_and_grad(
            CFG, p, i, l, m, pp=topo.pp_degree,
            num_microbatches=microbatches, vpp=vpp, deterministic=True)

    with mesh, nn.logical_axis_rules(list(rules)):
        loss, grads = jax.jit(jax.value_and_grad(f))(
            params_s, ids_s, labels_s, mask_s)
        loss2, grads2 = jax.jit(f_1f1b)(params_s, ids_s, labels_s,
                                        mask_s)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3),
        ref_grads, grads)
    # the explicit 1F1B schedule computes the identical loss/grads
    np.testing.assert_allclose(float(loss2), float(ref_loss), rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3),
        ref_grads, grads2)


def test_pipelined_loss_weighting_matches_accumulation(golden):
    """With masks that vary across microbatches, the pp loss equals the
    engine accumulation semantics: mean over microbatches of the
    per-microbatch masked mean (reference 1F1B micro-loss averaging)."""
    params, ids, labels, _, _, _ = golden
    rng = np.random.default_rng(3)
    mask = jnp.asarray((rng.random(ids.shape) > 0.4), jnp.float32)
    M = 4
    model = GPTForPretraining(CFG)
    per_mb = []
    for i in range(M):
        sl = slice(i * ids.shape[0] // M, (i + 1) * ids.shape[0] // M)
        logits = model.apply({"params": params}, ids[sl])
        per_mb.append(cross_entropy_loss(logits, labels[sl], mask[sl]))
    want = float(np.mean([float(x) for x in per_mb]))

    topo = TopologyConfig(pp_degree=2)
    mesh = build_mesh(topo, devices=jax.devices()[:2])
    set_mesh(mesh)
    rules = make_sharding_rules(topo)
    with mesh, nn.logical_axis_rules(list(rules)):
        got = jax.jit(lambda p: pipelined_lm_loss(
            CFG, p, ids, labels, mask, pp=2, num_microbatches=M,
            deterministic=True))(params)
    np.testing.assert_allclose(float(got), want, rtol=2e-5)


def test_1f1b_uses_less_activation_memory_than_gpipe():
    """The 1F1B property: with many microbatches the explicit schedule's
    temp (activation) memory is bounded by pipeline depth, while
    autodiff through the GPipe forward stashes every microbatch —
    XLA's own memory analysis shows the gap (the reference's reason
    for defaulting to 1F1B)."""
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_attention_heads=4, max_position_embeddings=64,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    params = nn.meta.unbox(GPTForPretraining(cfg).init(
        {"params": jax.random.key(0)},
        jnp.zeros((1, 8), jnp.int32)))["params"]
    B, S, M = 32, 32, 16
    ids = jnp.zeros((B, S), jnp.int32)
    mask = jnp.ones((B, S), jnp.float32)

    gpipe = jax.jit(jax.value_and_grad(lambda p: pipelined_lm_loss(
        cfg, p, ids, ids, mask, pp=1, num_microbatches=M,
        deterministic=True)))
    f1b = jax.jit(lambda p: pipelined_lm_loss_and_grad(
        cfg, p, ids, ids, mask, pp=1, num_microbatches=M,
        deterministic=True))
    mems = {}
    for name, fn in (("gpipe", gpipe), ("1f1b", f1b)):
        ma = fn.lower(params).compile().memory_analysis()
        if ma is None:
            pytest.skip("backend provides no memory analysis")
        mems[name] = ma.temp_size_in_bytes
    assert mems["1f1b"] < 0.8 * mems["gpipe"], mems


def test_decoder_params_sharded_over_pp():
    topo = TopologyConfig(pp_degree=2, mp_degree=2, dp_degree=2)
    mesh = build_mesh(topo)
    rules = make_sharding_rules(topo)
    logical_specs = nn.get_partition_spec(
        jax.eval_shape(GPTForPretraining(CFG).init,
                       {"params": jax.random.key(0)},
                       jnp.zeros((1, 8), jnp.int32)))
    shardings = nn.logical_to_mesh_sharding(logical_specs, mesh,
                                            list(rules))
    qkv = shardings["params"]["gpt"]["decoder"]["self_attn"][
        "qkv_proj"]["kernel"]
    assert qkv.spec == P("pp", None, None, "mp", None)
