"""Pin the TIPC-scraped TRAIN/EVAL line grammar (``loss:``/``ips:``).

The reference benchmark harness greps these lines
(``run_benchmark.sh:17-21``); the contract regexes live next to the
logger (``utils/log.py``) and these tests fail loudly if a logging
change — e.g. the telemetry ``hbm:`` suffix — breaks the scrape."""

import logging
import re

from paddlefleetx_tpu.core.module import LanguageModule
from paddlefleetx_tpu.utils.config import AttrDict
from paddlefleetx_tpu.utils.log import (
    EVAL_LINE_RE, EVAL_LINE_REQUIRED, TRAIN_LINE_RE,
    TRAIN_LINE_REQUIRED, logger,
)


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.lines = []

    def emit(self, record):
        self.lines.append(record.getMessage())


class _Module(LanguageModule):
    def get_model(self):
        return None


def _capture_lines(fn):
    h = _Capture()
    logger.addHandler(h)
    try:
        fn()
    finally:
        logger.removeHandler(h)
    return h.lines


def _module(nranks=8):
    m = _Module.__new__(_Module)
    m.configs = AttrDict({"Global": AttrDict({"global_batch_size": 16})})
    m.nranks = nranks
    return m


TRAIN_LOG = {"epoch": 1, "batch": 10, "loss": 4.123456789,
             "train_cost": 0.25, "lr": 5e-5, "max_seq_len": 32}


def test_train_line_matches_contract():
    lines = _capture_lines(
        lambda: _module().training_step_end(dict(TRAIN_LOG)))
    assert len(lines) == 1
    line = lines[0]
    assert re.fullmatch(TRAIN_LINE_RE, line), line
    for token in TRAIN_LINE_REQUIRED:
        assert token in line, (token, line)
    # the harness splits on 'ips:' and reads the number after it
    ips = float(line.split("ips:")[-1].split("tokens/s")[0])
    assert ips == round(16 * 32 / 0.25 / 8)


def test_eval_line_matches_contract():
    lines = _capture_lines(
        lambda: _module().validation_step_end(
            {"epoch": 1, "batch": 3, "loss": 4.5, "eval_cost": 0.5}))
    assert len(lines) == 1
    assert re.fullmatch(EVAL_LINE_RE, lines[0]), lines[0]
    for token in EVAL_LINE_REQUIRED:
        assert token in lines[0], (token, lines[0])


def test_hbm_suffix_keeps_grammar():
    """The telemetry HBM suffix rides AFTER every pinned field: the
    contract regex still matches as a prefix and every grep token is
    intact."""
    log = dict(TRAIN_LOG)
    log["hbm_bytes_in_use"] = int(3.5 * 2**30)
    log["hbm_peak_bytes"] = int(5 * 2**30)
    lines = _capture_lines(
        lambda: _module().training_step_end(log))
    line = lines[0]
    assert re.match(TRAIN_LINE_RE, line), line
    assert line.endswith(", hbm: 3.50G (peak 5.00G)"), line
    for token in TRAIN_LINE_REQUIRED:
        assert token in line
    # and 'ips:' scraping still yields the same number
    ips = float(line.split("ips:")[-1].split("tokens/s")[0])
    assert ips == round(16 * 32 / 0.25 / 8)


def test_no_hbm_suffix_without_sample():
    lines = _capture_lines(
        lambda: _module().training_step_end(dict(TRAIN_LOG)))
    assert "hbm" not in lines[0]
