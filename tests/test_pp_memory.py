"""Analytic per-stage HBM model + schedule resolution
(``parallel/pp_memory.py``): byte accounting, the budget ladder, and
the reject-before-trace contract for infeasible zb_h2 depths.
"""

import pytest

from paddlefleetx_tpu.parallel import pp_memory

MK = dict(microbatch_tokens=2 * 32, hidden_size=64, param_count=100_000,
          compute_dtype="float32", param_dtype="float32")


def _total(schedule, d=0, pp=4, vpp=1, **over):
    kw = {**MK, **over}
    return pp_memory.stage_memory_bytes(
        schedule=schedule, pp=pp, vpp=vpp, h2_depth=d,
        **kw)["total_bytes"]


def test_dtype_bytes():
    assert pp_memory.dtype_bytes("float32") == 4
    assert pp_memory.dtype_bytes("bfloat16") == 2
    assert pp_memory.dtype_bytes("bf16") == 2
    import numpy as np
    assert pp_memory.dtype_bytes(np.dtype("float32")) == 4
    with pytest.raises(ValueError, match="unknown dtype"):
        pp_memory.dtype_bytes("float77")


def test_stage_bytes_schedule_ordering():
    """1f1b < zb == zb_h2@0 < zb_h2@d, monotone in depth — the exact
    ladder the resolver walks."""
    b_1f1b = _total("1f1b")
    b_zb = _total("zb")
    assert b_1f1b < b_zb
    assert _total("zb_h2", 0) == b_zb
    prev = b_zb
    for d in range(1, 4):
        cur = _total("zb_h2", d)
        assert cur > prev
        prev = cur
    # the increment per depth step is exactly one microbatch
    # activation per vpp chunk (one extra cotangent-ring row)
    mb_act = MK["microbatch_tokens"] * MK["hidden_size"] * 4
    assert _total("zb_h2", 2) - _total("zb_h2", 1) == mb_act


def test_stage_bytes_dtype_aware():
    """bf16 compute halves the ring bytes; bf16 params halve the param
    term while grads stay fp32."""
    full = pp_memory.stage_memory_bytes(
        schedule="zb_h2", pp=4, h2_depth=3, **MK)
    half = pp_memory.stage_memory_bytes(
        schedule="zb_h2", pp=4, h2_depth=3,
        **{**MK, "compute_dtype": "bfloat16",
           "param_dtype": "bfloat16"})
    assert half["act_ring_bytes"] == full["act_ring_bytes"] // 2
    assert half["gstash_bytes"] == full["gstash_bytes"] // 2
    assert half["params_bytes"] == full["params_bytes"] // 2
    assert half["grads_bytes"] == full["grads_bytes"]  # fp32 accum


def test_hbm_budget_env_knob(monkeypatch):
    monkeypatch.setenv("PFX_PP_HBM_BUDGET_BYTES", "12345")
    assert pp_memory.hbm_budget_bytes() == 12345
    monkeypatch.setenv("PFX_PP_HBM_BUDGET_BYTES", "0")
    assert pp_memory.hbm_budget_bytes() is None
    monkeypatch.setenv("PFX_PP_HBM_BUDGET_BYTES", "lots")
    with pytest.raises(ValueError, match="not an integer"):
        pp_memory.hbm_budget_bytes()


def test_resolve_passthrough_and_unknown():
    r = pp_memory.resolve_pipeline_schedule("zb", pp=4)
    assert (r["schedule"], r["h2_depth"]) == ("zb", 0)
    r = pp_memory.resolve_pipeline_schedule("1F1B", pp=4)
    assert (r["schedule"], r["h2_depth"]) == ("1F1B", 0)
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        pp_memory.resolve_pipeline_schedule("zb_h9", pp=4)


def test_resolve_blind_is_optimistic_full_depth():
    """No budget info: zb_h2/zb_auto assume full depth K-1 (keeps CPU
    runs and the engine's estimate consistent) and say so."""
    for sched in ("zb_h2", "zb_auto"):
        r = pp_memory.resolve_pipeline_schedule(sched, pp=4)
        assert (r["schedule"], r["h2_depth"]) == ("zb_h2", 3)
        assert "no HBM budget information" in r["reason"] or \
            "without HBM budget" in r["reason"]
        assert r["predicted_stage_bytes"] is None


def test_resolve_zb_auto_budget_ladder():
    """zb_auto walks 1F1B -> zb -> zb_h2@d to the deepest feasible
    rung for the budget."""
    cases = [(_total("zb_h2", 3), ("zb_h2", 3)),
             (_total("zb_h2", 2), ("zb_h2", 2)),
             (_total("zb_h2", 1), ("zb_h2", 1)),
             (_total("zb"), ("zb", 0)),
             (_total("1f1b"), ("1F1B", 0))]
    for budget, want in cases:
        r = pp_memory.resolve_pipeline_schedule(
            "zb_auto", pp=4, budget_bytes=budget, mem_kwargs=MK)
        assert (r["schedule"], r["h2_depth"]) == want, (budget, r)
        assert r["predicted_stage_bytes"] <= budget


def test_resolve_zb_h2_rejects_infeasible_depth():
    """An explicitly configured depth that exceeds the budget raises a
    config-time ValueError — never an OOM at trace time."""
    with pytest.raises(ValueError, match="bytes per stage"):
        pp_memory.resolve_pipeline_schedule(
            "zb_h2", pp=4, requested_depth=3,
            budget_bytes=_total("zb"), mem_kwargs=MK)
    # depth -1 clamps to the deepest feasible depth instead
    r = pp_memory.resolve_pipeline_schedule(
        "zb_h2", pp=4, requested_depth=-1,
        budget_bytes=_total("zb_h2", 1), mem_kwargs=MK)
    assert (r["schedule"], r["h2_depth"]) == ("zb_h2", 1)
    # nothing feasible at all: zb_h2 refuses outright
    with pytest.raises(ValueError, match="any depth"):
        pp_memory.resolve_pipeline_schedule(
            "zb_h2", pp=4, requested_depth=-1,
            budget_bytes=_total("1f1b"), mem_kwargs=MK)


def test_module_rejects_infeasible_depth_before_trace(monkeypatch):
    """End to end through GPTModule._resolve_pp_schedule: a pinned
    budget below the requested depth's bytes raises before any
    pipeline tracing happens."""
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.models.gpt.config import GPTConfig
    from paddlefleetx_tpu.models.gpt.modules import GPTModule

    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                    num_attention_heads=4,
                    pipeline_schedule="zb_h2", zb_h2_depth=1)
    tokens = jnp.zeros((4, 16), jnp.int32)
    params = {"w": jnp.zeros((100,), jnp.float32)}
    mod = GPTModule.__new__(GPTModule)   # skip engine-level __init__
    mod.model_config = cfg
    monkeypatch.setenv("PFX_PP_HBM_BUDGET_BYTES", "1024")
    with pytest.raises(ValueError, match="bytes per stage"):
        mod._resolve_pp_schedule("zb_h2", params, tokens, pp=2,
                                 num_microbatches=4)
    # zb_auto under the same starvation degrades instead of raising
    sched, depth = mod._resolve_pp_schedule(
        "zb_auto", params, tokens, pp=2, num_microbatches=4)
    assert sched == "1F1B" and depth == 0
    # and with headroom it climbs back to full depth
    monkeypatch.setenv("PFX_PP_HBM_BUDGET_BYTES", str(1 << 40))
    sched, depth = mod._resolve_pp_schedule(
        "zb_auto", params, tokens, pp=2, num_microbatches=4)
    assert sched == "zb_h2" and depth == 1
