"""LogHistogram: fixed-memory quantiles vs the exact percentile.

The histogram is the latency substrate of PR 10: serving TTFT /
queue-wait / TPOT / tick and the engine's step time all ride it, and
``summary()``'s pinned ``ttft_p50_ms``/``ttft_p99_ms`` fields source
from it — so its quantile error bound (one log bucket, ~8% relative)
and its edge cases (empty, single sample, non-finite, under/overflow)
are pinned here against ``np.percentile`` ground truth.
"""

import math

import numpy as np
import pytest

from paddlefleetx_tpu.observability import metrics
from paddlefleetx_tpu.observability.histogram import LogHistogram


# -- quantile accuracy -------------------------------------------------


@pytest.mark.parametrize("dist,seed", [
    ("lognormal", 0), ("lognormal", 7), ("uniform", 1),
    ("exponential", 2),
])
def test_quantiles_within_bucket_tolerance(dist, seed):
    """p50/p90/p99 within one log bucket (ratio 10^(1/30) ≈ 8%) of
    the exact ``np.percentile`` over the same samples."""
    rng = np.random.default_rng(seed)
    if dist == "lognormal":
        xs = rng.lognormal(mean=3.0, sigma=1.0, size=5000)
    elif dist == "uniform":
        xs = rng.uniform(0.5, 500.0, size=5000)
    else:
        xs = rng.exponential(scale=40.0, size=5000)
    h = LogHistogram()
    for x in xs:
        h.observe(float(x))
    ratio = 10.0 ** (1.0 / 30.0)
    for p in (50, 90, 99):
        exact = float(np.percentile(xs, p))
        got = h.percentile(p)
        assert exact / ratio <= got <= exact * ratio, \
            f"p{p}: {got} vs exact {exact}"


def test_quantile_monotone_and_clamped():
    rng = np.random.default_rng(3)
    h = LogHistogram()
    xs = rng.lognormal(2.0, 1.5, size=2000)
    for x in xs:
        h.observe(float(x))
    qs = [h.quantile(q) for q in np.linspace(0.0, 1.0, 21)]
    assert qs == sorted(qs)                      # monotone in q
    assert qs[0] == pytest.approx(h.min)         # clamped to observed
    assert qs[-1] == pytest.approx(h.max)
    assert h.percentile(50) <= h.percentile(99)  # the summary pin


def test_single_sample_and_exact_edges():
    h = LogHistogram()
    h.observe(42.0)
    # everything clamps to the lone observation
    for p in (0, 50, 99, 100):
        assert h.percentile(p) == pytest.approx(42.0)
    assert h.count == 1
    assert h.sum == pytest.approx(42.0)


def test_empty_and_nonfinite():
    h = LogHistogram()
    assert h.count == 0
    assert h.quantile(0.5) == 0.0
    assert h.snapshot() == {"count": 0, "sum": 0.0, "buckets": []}
    h.observe(float("nan"))
    h.observe(float("inf"))
    h.observe(float("-inf"))
    assert h.count == 0    # non-finite samples are dropped, not binned


def test_underflow_and_overflow_buckets():
    h = LogHistogram(lo=1e-3, hi=1e3)
    h.observe(0.0)          # underflow (<= lo): bucket 0
    h.observe(-5.0)         # negative: also underflow, never lost
    h.observe(1e9)          # overflow: clamped to the last bucket
    assert h.count == 3
    assert h.min == -5.0 and h.max == 1e9
    # quantiles stay inside the observed range
    assert -5.0 <= h.quantile(0.5) <= 1e9


def test_fixed_memory_and_reset():
    h = LogHistogram()
    n_slots = len(h._counts)
    for i in range(100_000):
        h.observe(float(i % 977) + 0.5)
    assert len(h._counts) == n_slots   # O(buckets) forever
    h.reset()
    assert h.count == 0 and h.quantile(0.9) == 0.0


def test_cumulative_is_prometheus_shaped():
    h = LogHistogram()
    for x in (1.0, 2.0, 4.0, 400.0):
        h.observe(x)
    rows = list(h.cumulative())
    uppers = [u for u, _ in rows]
    cums = [c for _, c in rows]
    assert uppers == sorted(uppers)
    assert cums == sorted(cums)            # cumulative counts
    assert cums[-1] == h.count
    for x in (1.0, 2.0, 4.0, 400.0):       # every sample <= some upper
        assert any(x <= u for u in uppers)


# -- registry integration ----------------------------------------------


def test_registry_observe_snapshot_reset():
    reg = metrics.MetricsRegistry(enabled=True)
    for v in (5.0, 10.0, 20.0):
        reg.observe("x/lat_ms", v)
    h = reg.histogram("x/lat_ms")
    assert h is not None and h.count == 3
    snap = reg.snapshot()
    hs = snap["histograms"]["x/lat_ms"]
    assert hs["count"] == 3
    assert hs["sum"] == pytest.approx(35.0)
    assert hs["p50"] <= hs["p99"]
    reg.reset()
    assert reg.histogram("x/lat_ms").count == 0


def test_registry_observe_disabled_is_noop():
    reg = metrics.MetricsRegistry(enabled=False)
    reg.observe("x/lat_ms", 5.0)
    assert reg.histogram("x/lat_ms") is None
    assert reg.snapshot()["histograms"] == {}


def test_module_level_observe_gated_on_global_enable():
    prev = metrics.get_registry().enabled
    try:
        metrics.set_enabled(False)
        metrics.observe("gate/check_ms", 1.0)
        assert metrics.get_registry().histogram("gate/check_ms") is None
        metrics.set_enabled(True)
        metrics.observe("gate/check_ms", 1.0)
        h = metrics.get_registry().histogram("gate/check_ms")
        assert h is not None and h.count == 1
    finally:
        metrics.get_registry().reset()
        metrics.set_enabled(prev)


def test_bucket_width_matches_advertised_ratio():
    """The docs promise ~8% relative bucket width (30 buckets per
    decade); the bounds must actually deliver it."""
    h = LogHistogram()
    lower, upper = h.bounds(10)
    assert upper / lower == pytest.approx(10.0 ** (1.0 / 30.0))
    assert math.log10(upper / lower) * 30 == pytest.approx(1.0)
