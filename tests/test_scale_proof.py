"""Scale-proof: the big recipes' topologies hold on virtual meshes.

The reference's TIPC harness validates large configs by shrinking the
model (num_layers=4, run_benchmark.sh) and running the real topology.
Same trick here: the REAL 6.7B sharding16 YAML runs its 16-way ZeRO-2
topology on a 16-device virtual CPU mesh through the TIPC driver
(reference ``benchmarks/test_tipc/gpt/hybrid_parallel/N*``).
"""

import json
import os
import subprocess
import sys

import numpy as np

from test_data import make_corpus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_6_7B_sharding16_topology_on_16_device_mesh(tmp_path):
    make_corpus(tmp_path, n_docs=60, doc_len_range=(20, 60), vocab=128,
                eos=127)
    cmd = [
        sys.executable, os.path.join(REPO, "benchmarks",
                                     "run_benchmark.py"),
        "--model_item", "gpt_6.7B_sharding16_scaled",
        "--config",
        os.path.join(REPO, "configs/nlp/gpt/"
                           "pretrain_gpt_6.7B_sharding16.yaml"),
        "--max_steps", "3", "--cpu-devices", "16", "--skip_steps", "0",
        "--overrides",
        # TIPC shrink (reference run_benchmark.sh: 4 layers) — the
        # sharding16/stage-2 topology is what's under test
        "Model.num_layers=4", "Model.hidden_size=128",
        "Model.num_attention_heads=4", "Model.ffn_hidden_size=256",
        "Model.vocab_size=128", "Model.max_position_embeddings=64",
        "Model.hidden_dropout_prob=0.0",
        "Model.attention_probs_dropout_prob=0.0",
        "Model.use_flash_attention=False",
        "Global.local_batch_size=1", "Global.micro_batch_size=1",
        "Engine.logging_freq=1", "Engine.eval_freq=100000",
        f"Engine.save_load.output_dir={tmp_path / 'out'}",
        "Engine.save_load.save_steps=100000",
        f"Data.Train.dataset.input_dir={tmp_path}",
        "Data.Train.dataset.split=[3,1,0]",
        "Data.Train.dataset.num_samples=64",
        "Data.Train.dataset.mode=Train", "Data.Train.dataset.eos_id=127",
        "Data.Train.dataset.max_seq_len=64",
        "Data.Train.dataset.build_data_file=True",
        f"Data.Eval.dataset.input_dir={tmp_path}",
        "Data.Eval.dataset.split=[3,1,0]",
        "Data.Eval.dataset.num_samples=16",
        "Data.Eval.dataset.mode=Eval", "Data.Eval.dataset.eos_id=127",
        "Data.Eval.dataset.max_seq_len=64",
        "Data.Eval.dataset.build_data_file=True",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=900, cwd=REPO)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"], result
    assert result["ips"] > 0                      # throughput parsed
    assert np.isfinite(result["last_loss"])       # topology executes
