"""Scale-proof: the big recipes' topologies hold on virtual meshes.

The reference's TIPC harness validates large configs by shrinking the
model (num_layers=4, run_benchmark.sh) and running the real topology.
Same trick here: the REAL big-model YAMLs run their full device
topologies on virtual CPU meshes through the TIPC driver
(reference ``benchmarks/test_tipc/gpt/hybrid_parallel/N*``).
"""

import json
import os
import subprocess
import sys

import numpy as np

from test_data import make_corpus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_scale_proof(tmp_path, model_item, config, devices, max_steps,
                     shrink_overrides, seq_len=64):
    """TIPC-shrink a real recipe and run its full topology on a
    virtual CPU mesh; returns the driver's parsed result line."""
    make_corpus(tmp_path, n_docs=60, doc_len_range=(20, 60), vocab=128,
                eos=127)
    data_overrides = []
    for mode, samples in (("Train", 64), ("Eval", 16)):
        data_overrides += [
            f"Data.{mode}.dataset.input_dir={tmp_path}",
            f"Data.{mode}.dataset.split=[3,1,0]",
            f"Data.{mode}.dataset.num_samples={samples}",
            f"Data.{mode}.dataset.mode={mode}",
            f"Data.{mode}.dataset.eos_id=127",
            f"Data.{mode}.dataset.max_seq_len={seq_len}",
            f"Data.{mode}.dataset.build_data_file=True",
        ]
    cmd = [
        sys.executable,
        os.path.join(REPO, "benchmarks", "run_benchmark.py"),
        "--model_item", model_item,
        "--config", os.path.join(REPO, config),
        "--max_steps", str(max_steps), "--cpu-devices", str(devices),
        "--skip_steps", "0",
        "--overrides",
        # TIPC shrink (reference run_benchmark.sh shrinks the model;
        # the full device topology is what's under test)
        "Model.vocab_size=128", "Model.max_position_embeddings=64",
        "Model.hidden_dropout_prob=0.0",
        "Model.attention_probs_dropout_prob=0.0",
        "Model.use_flash_attention=False",
        "Engine.logging_freq=1", "Engine.eval_freq=100000",
        f"Engine.save_load.output_dir={tmp_path / 'out'}",
        "Engine.save_load.save_steps=100000",
        *shrink_overrides, *data_overrides,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=900, cwd=REPO)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"], result
    assert result["ips"] > 0                      # throughput parsed
    assert np.isfinite(result["last_loss"])       # topology executes
    return result


def test_6_7B_sharding16_topology_on_16_device_mesh(tmp_path):
    _run_scale_proof(
        tmp_path, "gpt_6.7B_sharding16_scaled",
        "configs/nlp/gpt/pretrain_gpt_6.7B_sharding16.yaml",
        devices=16, max_steps=3,
        shrink_overrides=[
            "Model.num_layers=4", "Model.hidden_size=128",
            "Model.num_attention_heads=4", "Model.ffn_hidden_size=256",
            "Global.local_batch_size=1", "Global.micro_batch_size=1",
        ])


def test_175B_mp8_pp16_topology_on_128_device_mesh(tmp_path):
    """The flagship 175B recipe's REAL mp8 x pp16 topology (128-way)
    executes end to end — layers/widths TIPC-shrunk, the 1F1B pipeline
    schedule and the 8-way tensor sharding are what's under test.
    Measured ~90s wall on the CI host."""
    _run_scale_proof(
        tmp_path, "gpt_175B_mp8_pp16_scaled",
        "configs/nlp/gpt/pretrain_gpt_175B_mp8_pp16.yaml",
        devices=128, max_steps=2, seq_len=32,
        shrink_overrides=[
            "Model.num_layers=16", "Model.hidden_size=128",
            "Model.num_attention_heads=8", "Model.ffn_hidden_size=256",
            "Global.global_batch_size=16", "Global.local_batch_size=16",
            "Global.micro_batch_size=1",
        ])


def test_6_7B_v5p64_topology_on_64_device_mesh(tmp_path):
    """The v5p-64 north-star recipe (mp4 x fsdp16 ZeRO-3 + Megatron-SP
    + flash + chunked loss) executes its full 64-chip topology
    (VERDICT r3 #2 done-criterion)."""
    _run_scale_proof(
        tmp_path, "gpt_6.7B_v5p64_scaled",
        "configs/nlp/gpt/pretrain_gpt_6.7B_v5p64.yaml",
        devices=64, max_steps=3,
        shrink_overrides=[
            "Model.num_layers=4", "Model.hidden_size=128",
            "Model.num_attention_heads=4", "Model.ffn_hidden_size=256",
            "Model.loss_chunks=2",
            "Global.global_batch_size=32",
            "Global.local_batch_size=2",
            "Global.micro_batch_size=1",
            "Engine.accumulate_steps=2",
        ])
