"""Every TIPC topology script executes, tiny, on its virtual mesh.

The reference's TIPC matrix (``benchmarks/test_tipc/gpt/
hybrid_parallel/N*``) is its perf CI; these tests run the ACTUAL shell
scripts — not reconstructions — with the model shrunk via appended
overrides (the scripts forward ``"$@"`` to the driver precisely for
this) and the device count from the script's N*C* directory on the
virtual CPU mesh, asserting each topology reaches a finite loss.
"""

import glob
import json
import os
import re
import subprocess

import numpy as np
import pytest

from test_data import make_corpus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = sorted(glob.glob(os.path.join(
    REPO, "benchmarks", "test_tipc", "gpt", "hybrid_parallel",
    "N*", "*.sh")))

assert len(SCRIPTS) >= 10, SCRIPTS  # 2 N1C1 + 2 N1C8 + 6 N4C32


def _devices_of(script: str) -> int:
    m = re.search(r"N(\d+)C(\d+)", os.path.dirname(script))
    return int(m.group(2))


@pytest.mark.parametrize(
    "script", SCRIPTS, ids=[os.path.relpath(
        s, os.path.join(REPO, "benchmarks", "test_tipc", "gpt",
                        "hybrid_parallel")) for s in SCRIPTS])
def test_tipc_script_topology_executes(script, tmp_path):
    make_corpus(tmp_path, n_docs=40, doc_len_range=(20, 40), vocab=128,
                eos=127)
    shrink = [
        "Model.vocab_size=128", "Model.max_position_embeddings=32",
        "Model.hidden_size=64", "Model.num_attention_heads=8",
        "Model.ffn_hidden_size=128", "Model.num_layers=4",
        "Model.hidden_dropout_prob=0.0",
        "Model.attention_probs_dropout_prob=0.0",
        "Model.use_flash_attention=False",
        "Global.local_batch_size=8", "Global.micro_batch_size=2",
        "Engine.logging_freq=1",
        f"Engine.save_load.output_dir={tmp_path / 'out'}",
        "Engine.save_load.save_steps=100000",
    ]
    for mode, samples in (("Train", 32), ("Eval", 8)):
        shrink += [
            f"Data.{mode}.dataset.split=[3,1,0]",
            f"Data.{mode}.dataset.num_samples={samples}",
            f"Data.{mode}.dataset.mode={mode}",
            f"Data.{mode}.dataset.eos_id=127",
            "Data.%s.dataset.max_seq_len=32" % mode,
            f"Data.{mode}.dataset.build_data_file=True",
        ]
    env = dict(os.environ)
    env.update(CPU_DEVICES=str(_devices_of(script)), MAX_STEPS="2",
               DATA_DIR=str(tmp_path))
    proc = subprocess.run(
        ["bash", script, "--skip_steps", "0", "--overrides", *shrink],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"], result
    assert np.isfinite(result["last_loss"]), result
