"""PageAllocator: the host-side state machine under the paged KV cache.

The allocator is pure bookkeeping (no device traffic), which makes it
cheap to hammer: the randomized trace test below replays thousands of
admit / grow / COW-split / evict / preempt transitions — the exact
moves ``core/serving.py`` makes between decode ticks — and asserts
:meth:`PageAllocator.check`'s invariants after every single one. The
deterministic tests pin each transition's contract on its own.
"""

import numpy as np
import pytest

from paddlefleetx_tpu.core.paging import (
    NULL_PAGE, PageAllocator, PagePoolExhausted, page_prefix_keys,
    prompt_key,
)


# -- content keys ------------------------------------------------------


def test_page_prefix_keys_chain_over_full_pages():
    toks = list(range(300))
    keys = page_prefix_keys(toks, 128)
    assert len(keys) == 2  # 300 // 128 full pages; the tail hashes not
    # chain property: key j digests pages 0..j, so sharing any prefix
    # of full pages means sharing the leading keys
    other = toks[:256] + [999] * 44
    assert page_prefix_keys(other, 128) == keys
    diverge = toks[:128] + [7] + toks[129:]
    keys2 = page_prefix_keys(diverge, 128)
    assert keys2[0] == keys[0] and keys2[1] != keys[1]


def test_prompt_key_is_length_tagged():
    a, b = list(range(10)), list(range(12))
    assert prompt_key(a) != prompt_key(b)
    assert prompt_key(a) == prompt_key(list(range(10)))
    assert prompt_key(a).startswith("L10:")


# -- allocator basics --------------------------------------------------


def test_alloc_release_roundtrip():
    a = PageAllocator(num_pages=4, page_size=128)
    assert a.free_pages == 3 and a.pages_in_use == 0
    p1, p2 = a.alloc(), a.alloc()
    assert NULL_PAGE not in (p1, p2) and p1 != p2
    assert a.refcount(p1) == 1 and a.pages_in_use == 2
    assert a.release(p1) is True  # freed
    assert a.refcount(p1) == 0 and a.free_pages == 2
    a.check()


def test_alloc_is_deterministic_low_ids_first():
    a = PageAllocator(num_pages=5, page_size=128)
    assert [a.alloc() for _ in range(4)] == [1, 2, 3, 4]
    with pytest.raises(PagePoolExhausted):
        a.alloc()
    assert a.try_alloc() is None


def test_retain_release_refcounting():
    a = PageAllocator(num_pages=3, page_size=128)
    p = a.alloc()
    assert a.retain(p) == 2
    assert a.release(p) is False  # still referenced
    assert a.refcount(p) == 1
    assert a.release(p) is True
    with pytest.raises(ValueError):
        a.release(p)  # double free
    with pytest.raises(ValueError):
        a.retain(p)  # retain of a free page
    a.check()


def test_constructor_validation():
    with pytest.raises(ValueError):
        PageAllocator(num_pages=1, page_size=128)  # only the null page
    with pytest.raises(ValueError):
        PageAllocator(num_pages=4, page_size=0)


# -- registries --------------------------------------------------------


def test_prefix_registry_first_writer_wins_and_dies_with_page():
    a = PageAllocator(num_pages=5, page_size=2)
    p1, p2 = a.alloc(), a.alloc()
    a.register_prefix("k", p1)
    a.register_prefix("k", p2)  # late duplicate: ignored
    assert a.lookup_prefix("k") == p1
    a.release(p1)
    assert a.lookup_prefix("k") is None  # entry died with the page
    a.check()
    with pytest.raises(ValueError):
        a.register_prefix("k2", p1)  # page is free now


def test_prompt_registry_shares_pages_and_payload():
    a = PageAllocator(num_pages=6, page_size=2)
    pages = [a.alloc(), a.alloc()]
    a.register_prompt("P", pages, payload="logits-row")
    got = a.lookup_prompt("P")
    assert got == (tuple(pages), "logits-row")
    # consumer retains every page it maps (the documented contract)
    for p in got[0]:
        a.retain(p)
    # producer evicts; the entry survives because the consumer's refs
    # keep every member page live
    for p in pages:
        assert a.release(p) is False
    assert a.lookup_prompt("P") is not None
    a.check()
    # consumer evicts too -> pages free -> entry and its reverse maps
    # on OTHER member pages are dropped
    for p in pages:
        assert a.release(p) is True
    assert a.lookup_prompt("P") is None
    a.check()


def test_prompt_registry_partial_release_drops_whole_entry():
    # one member page dying invalidates the page list, so the entry
    # must vanish even though the other page is still live
    a = PageAllocator(num_pages=6, page_size=2)
    p1, p2 = a.alloc(), a.alloc()
    a.register_prompt("P", [p1, p2], payload=None)
    a.release(p1)
    assert a.lookup_prompt("P") is None
    assert a.refcount(p2) == 1  # survivor unaffected
    a.check()


def test_register_prompt_rejects_free_pages():
    a = PageAllocator(num_pages=4, page_size=2)
    p = a.alloc()
    a.release(p)
    with pytest.raises(ValueError):
        a.register_prompt("P", [p], payload=None)


# -- cross-server handoff invariants -----------------------------------
#
# core/fleet.py moves a prefilled sequence between two GenerationServers
# by (a) retaining the source pages for the duration of the export
# (kv_export), (b) allocating fresh ids on the destination pool and
# registering the same content keys there (kv_import), and (c) pinning
# the imported pages until the request finishes (kv_import_release).
# These tests replay that dance at the allocator level and run
# ``check()`` on both sides after every phase.


def test_export_retain_keeps_registry_alive_past_source_release():
    src = PageAllocator(num_pages=6, page_size=2)
    toks = [3, 1, 4, 1]  # two full pages
    pages = [src.alloc(), src.alloc()]
    for key, page in zip(page_prefix_keys(toks, 2), pages):
        src.register_prefix(key, page)
    src.register_prompt(prompt_key(toks), pages, payload="last-logits")
    # export pins every page (what kv_export does)
    for p in pages:
        src.retain(p)
    # the source request finishes and its slot is evicted
    for p in pages:
        assert src.release(p) is False
    # registries must survive on the strength of the export pins alone
    assert src.lookup_prompt(prompt_key(toks)) is not None
    assert src.lookup_prefix(page_prefix_keys(toks, 2)[0]) == pages[0]
    src.check()
    # export done (gather dispatched) -> drop the pins -> all gone
    for p in pages:
        assert src.release(p) is True
    assert src.lookup_prompt(prompt_key(toks)) is None
    src.check()


def test_import_remaps_page_ids_and_pins_until_release():
    toks = [3, 1, 4, 1]
    src = PageAllocator(num_pages=6, page_size=2)
    src_pages = [src.alloc(), src.alloc()]
    src.register_prompt(prompt_key(toks), src_pages, payload="logits")

    # destination pool has different occupancy, so the same content
    # lands on different page ids — the page table must be remapped,
    # never copied verbatim
    dst = PageAllocator(num_pages=8, page_size=2)
    occupied = [dst.alloc() for _ in range(3)]
    dst_pages = [dst.alloc() for _ in src_pages]
    assert set(dst_pages).isdisjoint(src_pages[:1]) or \
        dst_pages != src_pages  # ids genuinely remapped
    for key, page in zip(page_prefix_keys(toks, 2), dst_pages):
        dst.register_prefix(key, page)
    dst.register_prompt(prompt_key(toks), dst_pages, payload="logits")
    src.check()
    dst.check()

    # a consumer on the destination admits via the registry and retains
    got_pages, payload = dst.lookup_prompt(prompt_key(toks))
    assert got_pages == tuple(dst_pages) and payload == "logits"
    for p in got_pages:
        dst.retain(p)
    # import pin drops (kv_import_release); consumer refs keep it live
    for p in dst_pages:
        assert dst.release(p) is False
    assert dst.lookup_prompt(prompt_key(toks)) is not None
    dst.check()
    # consumer finishes -> content evaporates from the destination
    for p in got_pages:
        assert dst.release(p) is True
    assert dst.lookup_prompt(prompt_key(toks)) is None
    assert dst.lookup_prefix(page_prefix_keys(toks, 2)[0]) is None
    for p in occupied:
        dst.release(p)
    dst.check()
    # ...and the source was never perturbed by any of it
    assert src.lookup_prompt(prompt_key(toks)) is not None
    src.check()


def test_import_is_idempotent_under_registry_collision():
    # two routers racing the same prefix into one destination: the
    # second register_prefix is a no-op (first writer wins) and both
    # sides can release their own pages without corrupting the winner
    toks = list(range(4))
    key = page_prefix_keys(toks, 2)[0]
    dst = PageAllocator(num_pages=6, page_size=2)
    p_win, p_lose = dst.alloc(), dst.alloc()
    dst.register_prefix(key, p_win)
    dst.register_prefix(key, p_lose)  # ignored
    assert dst.lookup_prefix(key) == p_win
    assert dst.release(p_lose) is True  # loser frees its copy
    assert dst.lookup_prefix(key) == p_win
    dst.check()
    dst.release(p_win)
    assert dst.lookup_prefix(key) is None
    dst.check()


# -- host tier: spill / promote / LRU / snapshot -----------------------
#
# The hierarchical cache (docs/inference.md) moves a registered page's
# REGISTRATIONS to a host page id at refcount zero instead of dropping
# them; a later registry hit promotes them back onto a fresh device id.
# Host ids live in ``num_pages .. num_pages + host_pages - 1`` and are
# never mapped by a page table, so COW safety is structural.


def test_spill_moves_registrations_and_frees_device_page():
    a = PageAllocator(num_pages=4, page_size=2, host_pages=2)
    p = a.alloc()
    a.register_prefix("k", p)
    a.register_prompt("P", [p], payload="row")
    hpid = a.spill(p)
    assert hpid is not None and hpid >= 4 and a.is_host(hpid)
    assert a.refcount(p) == 0 and a.free_pages == 3  # device page freed
    assert a.lookup_prefix("k") == hpid
    assert a.lookup_prompt("P") == ((hpid,), "row")
    assert a.page_registered(hpid) and a.host_pages_resident == 1
    assert a.stats["spills"] == 1
    a.check()


def test_spill_rejects_bad_refcounts_and_unregistered_pages():
    a = PageAllocator(num_pages=4, page_size=2, host_pages=2)
    p = a.alloc()
    a.retain(p)
    with pytest.raises(ValueError):
        a.spill(p)  # refcount 2: someone still maps it
    a.release(p)
    # unregistered page: nothing to keep warm — caller must release()
    assert a.spill(p) is None
    assert a.refcount(p) == 1  # NOT freed by the refusal
    a.release(p)
    # no tier configured: spill is always a refusal
    b = PageAllocator(num_pages=4, page_size=2)
    q = b.alloc()
    b.register_prefix("k", q)
    assert b.spill(q) is None
    a.check()
    b.check()


def test_promote_restores_device_residency():
    a = PageAllocator(num_pages=4, page_size=2, host_pages=2)
    p = a.alloc()
    a.register_prefix("k", p)
    hpid = a.spill(p)
    fresh = a.alloc()  # the admitting request's page
    a.promote(hpid, fresh)
    assert a.lookup_prefix("k") == fresh
    assert a.host_pages_resident == 0 and not a.is_host(hpid)
    assert a.refcount(fresh) == 1  # the admitter's reference
    assert a.stats["rehydrates"] == 1
    a.check()
    with pytest.raises(ValueError):
        a.promote(hpid, fresh)  # hpid no longer resident


def test_host_tier_lru_eviction_drops_oldest_registrations():
    a = PageAllocator(num_pages=6, page_size=2, host_pages=2)
    pids = [a.alloc() for _ in range(3)]
    for i, p in enumerate(pids):
        a.register_prefix(f"k{i}", p)
    h0 = a.spill(pids[0])
    a.spill(pids[1])
    a.spill(pids[2])  # tier full: h0 (oldest) is evicted to make room
    assert a.lookup_prefix("k0") is None
    assert a.lookup_prefix("k1") is not None
    assert a.lookup_prefix("k2") is not None
    assert a.pop_host_evicted() == [h0]
    assert a.pop_host_evicted() == []  # return-and-clear
    assert a.stats["host_evictions"] == 1
    a.check()


def test_prompt_entry_spanning_tiers_cascades_on_member_death():
    # a prompt entry with one hosted and one live member: the live
    # member dying invalidates the page list, and the hosted member —
    # now carrying no registration — must be evicted from the tier,
    # not leak in it
    a = PageAllocator(num_pages=6, page_size=2, host_pages=2)
    p1, p2 = a.alloc(), a.alloc()
    a.register_prompt("P", [p1, p2], payload=None)
    a.register_prefix("k", p1)  # keeps p1 spillable on its own
    h1 = a.spill(p1)
    assert a.lookup_prompt("P") == ((h1, p2), None)
    a.release(p2)
    assert a.lookup_prompt("P") is None
    assert a.host_pages_resident == 1  # h1 lives on via its prefix key
    # now kill the prefix entry's only registration via a live page
    fresh = a.alloc()
    a.promote(h1, fresh)
    a.release(fresh)
    assert a.host_pages_resident == 0 and a.lookup_prefix("k") is None
    a.check()


def test_host_snapshot_and_import_roundtrip():
    a = PageAllocator(num_pages=4, page_size=2, host_pages=3)
    p1, p2 = a.alloc(), a.alloc()
    a.register_prefix("k", p1)
    a.register_prompt("P", [p1, p2], payload="row")
    h1 = a.spill(p1)
    h2 = a.spill(p2)
    prefixes, prompts = a.host_snapshot()
    assert prefixes == {"k": h1}
    assert prompts == {"P": ([h1, h2], "row")}
    a.check()
    # a fresh allocator (the restarted replica) adopts the snapshot
    b = PageAllocator(num_pages=4, page_size=2, host_pages=2)
    nh1, nh2 = b.host_import(), b.host_import()
    assert nh1 is not None and nh2 is not None
    assert b.host_import() is None  # full: import never evicts
    b.register_prefix("k", nh1)
    b.register_prompt("P", [nh1, nh2], payload="row")
    assert b.lookup_prefix("k") == nh1
    assert b.host_pages_resident == 2
    b.check()
    # orphan sweep: an imported page that ended up unregistered goes
    c = PageAllocator(num_pages=4, page_size=2, host_pages=2)
    orphan = c.host_import()
    assert orphan is not None
    c.sweep_host_orphans()
    assert c.host_pages_resident == 0
    assert c.pop_host_evicted() == [orphan]
    c.check()


def test_host_generation_tags_residencies_and_evict_host():
    # host ids are recycled by the LRU, so ids alone cannot name a
    # residency: host_generation must differ across recycles (the
    # byte-store owner's stale-spill guard), and evict_host must let
    # the owner retire a residency whose bytes it lost (failed spill)
    a = PageAllocator(num_pages=4, page_size=2, host_pages=1)
    p = a.alloc()
    a.register_prefix("k", p)
    h = a.spill(p)
    g1 = a.host_generation(h)
    assert g1 is not None
    a.evict_host(h)
    assert a.host_generation(h) is None  # non-resident: no generation
    assert a.lookup_prefix("k") is None  # registrations died with it
    assert a.pop_host_evicted() == [h]
    a.evict_host(h)  # already gone: a no-op, not an error
    assert a.pop_host_evicted() == []
    p2 = a.alloc()
    a.register_prefix("k2", p2)
    h2 = a.spill(p2)
    assert h2 == h  # the id was recycled...
    assert a.host_generation(h2) > g1  # ...under a NEW generation
    a.check()


def test_check_catches_cross_tier_corruption():
    a = PageAllocator(num_pages=4, page_size=2, host_pages=2)
    p = a.alloc()
    a.register_prefix("k", p)
    hpid = a.spill(p)
    # no pid may be simultaneously free and host-resident
    a._free.append(hpid)
    with pytest.raises(AssertionError):
        a.check()
    a._free.remove(hpid)
    a.check()
    # ...nor live (refcounted) and host-resident
    a._ref[hpid] = 1
    with pytest.raises(AssertionError):
        a.check()
    del a._ref[hpid]
    a.check()
    # a hosted page carrying no registration is a leak
    a._page_prefix_keys.pop(hpid)
    with pytest.raises(AssertionError):
        a.check()


# -- randomized state-machine trace ------------------------------------


def test_randomized_admit_evict_preempt_trace():
    """Replay the server's transition mix against a model: admissions
    that share via both registries, decode growth, COW splits, and
    evict/preempt (both release), with ``check()`` after every step
    and an independent per-request page ledger cross-checked at the
    end of every request's life."""
    rng = np.random.default_rng(0)
    page = 4
    a = PageAllocator(num_pages=17, page_size=page)  # 16 usable
    live = {}  # req id -> list of (pid, shared_bool at map time)
    next_id = 0
    for step in range(3000):
        op = rng.choice(["admit", "grow", "cow", "evict"])
        if op == "admit":
            # random prompt from a tiny pool so prefix/prompt hits occur
            base = rng.integers(0, 3)
            L = int(rng.integers(1, 3 * page + 1))
            toks = [int(base)] * L  # content-determined sharing
            hit = a.lookup_prompt(prompt_key(toks))
            pages = []
            if hit is not None:
                for pid in hit[0]:
                    a.retain(pid)
                    pages.append(pid)
            else:
                keys = page_prefix_keys(toks, page)[:(L - 1) // page]
                owned_from = 0
                for k in keys:
                    pid = a.lookup_prefix(k)
                    if pid is None:
                        break
                    a.retain(pid)
                    pages.append(pid)
                    owned_from += 1
                need = -(-L // page) - owned_from
                got = []
                for _ in range(need):
                    pid = a.try_alloc()
                    if pid is None:
                        break
                    got.append(pid)
                if len(got) < need:  # pool full: roll back this admit
                    for pid in got + pages:
                        a.release(pid)
                    a.check()
                    continue
                pages += got
                for j, k in enumerate(keys):
                    a.register_prefix(k, pages[j])
                a.register_prompt(prompt_key(toks), pages, payload=L)
            live[next_id] = pages
            next_id += 1
        elif op == "grow" and live:
            rid = int(rng.choice(list(live)))
            pid = a.try_alloc()
            if pid is not None:
                live[rid].append(pid)
        elif op == "cow" and live:
            rid = int(rng.choice(list(live)))
            pages = live[rid]
            j = int(rng.integers(0, len(pages)))
            if a.refcount(pages[j]) > 1:  # the server's write gate
                new = a.try_alloc()
                if new is not None:
                    a.release(pages[j])
                    pages[j] = new
                    a.stats["cow_splits"] += 1
        elif op == "evict" and live:
            rid = int(rng.choice(list(live)))
            for pid in live.pop(rid):
                a.release(pid)
        a.check()
        # cross-check: pages_in_use equals the distinct pages the
        # ledger references, and every refcount matches the ledger
        refs = {}
        for pages in live.values():
            for pid in pages:
                refs[pid] = refs.get(pid, 0) + 1
        assert a.pages_in_use == len(refs)
        for pid, n in refs.items():
            assert a.refcount(pid) == n, (step, pid)
    # drain everything: the pool must come back whole
    for rid in list(live):
        for pid in live.pop(rid):
            a.release(pid)
    a.check()
    assert a.pages_in_use == 0 and a.free_pages == 16
    assert a.stats["allocs"] == a.stats["frees"]


def test_randomized_tiered_trace_spill_rehydrate_cow():
    """The same transition mix over a TWO-tier allocator: evictions of
    registered last-ref pages spill instead of freeing (what
    ``core/serving.py::_drain_spills`` does), registry hits that land
    on host ids rehydrate through ``try_alloc`` + ``promote`` (what
    ``_rehydrate`` does), and COW stays device-only structurally —
    the ledger never references a host id. ``check()``'s cross-tier
    invariant runs after every step; the final drain proves neither
    tier leaks."""
    rng = np.random.default_rng(7)
    page = 4
    a = PageAllocator(num_pages=13, page_size=page, host_pages=4)
    live = {}
    next_id = 0
    spills = rehydrates = 0
    for step in range(3000):
        op = rng.choice(["admit", "grow", "cow", "evict"])
        if op == "admit":
            base = rng.integers(0, 3)
            L = int(rng.integers(1, 3 * page + 1))
            toks = [int(base)] * L
            hit = a.lookup_prompt(prompt_key(toks))
            pages = []
            ok = True
            if hit is not None:
                for pid in hit[0]:
                    if a.is_host(pid):
                        fresh = a.try_alloc()
                        if fresh is None:
                            ok = False
                            break
                        a.promote(pid, fresh)
                        rehydrates += 1
                        pages.append(fresh)
                    else:
                        a.retain(pid)
                        pages.append(pid)
                if not ok:  # pool full mid-rehydrate: roll back
                    for pid in pages:
                        a.release(pid)
                    a.check()
                    continue
            else:
                keys = page_prefix_keys(toks, page)[:(L - 1) // page]
                owned_from = 0
                for k in keys:
                    pid = a.lookup_prefix(k)
                    if pid is None:
                        break
                    if a.is_host(pid):
                        fresh = a.try_alloc()
                        if fresh is None:
                            break
                        a.promote(pid, fresh)
                        rehydrates += 1
                        pages.append(fresh)
                    else:
                        a.retain(pid)
                        pages.append(pid)
                    owned_from += 1
                need = -(-L // page) - owned_from
                got = []
                for _ in range(need):
                    pid = a.try_alloc()
                    if pid is None:
                        break
                    got.append(pid)
                if len(got) < need:
                    for pid in got + pages:
                        a.release(pid)
                    a.check()
                    continue
                pages += got
                for j, k in enumerate(keys):
                    a.register_prefix(k, pages[j])
                a.register_prompt(prompt_key(toks), pages, payload=L)
            live[next_id] = pages
            next_id += 1
        elif op == "grow" and live:
            rid = int(rng.choice(list(live)))
            pid = a.try_alloc()
            if pid is not None:
                live[rid].append(pid)
        elif op == "cow" and live:
            rid = int(rng.choice(list(live)))
            pages = live[rid]
            j = int(rng.integers(0, len(pages)))
            assert not a.is_host(pages[j])  # structural COW safety
            if a.refcount(pages[j]) > 1:
                new = a.try_alloc()
                if new is not None:
                    a.release(pages[j])
                    pages[j] = new
                    a.stats["cow_splits"] += 1
        elif op == "evict" and live:
            rid = int(rng.choice(list(live)))
            for pid in live.pop(rid):
                # the serving release path: last ref on a registered
                # page tiers down (sometimes — admission pressure can
                # also just release, e.g. _alloc_or_preempt reclaims)
                if a.refcount(pid) == 1 and a.page_registered(pid) \
                        and rng.random() < 0.7:
                    if a.spill(pid) is not None:
                        spills += 1
                        continue
                a.release(pid)
        a.check()
        refs = {}
        for pages in live.values():
            for pid in pages:
                assert not a.is_host(pid)  # host ids never mapped
                refs[pid] = refs.get(pid, 0) + 1
        assert a.pages_in_use == len(refs)
        for pid, n in refs.items():
            assert a.refcount(pid) == n, (step, pid)
    # the trace must actually have exercised the tier
    assert spills > 100 and rehydrates > 10
    assert a.stats["spills"] == spills
    assert a.stats["rehydrates"] == rehydrates
    # drain: device pool comes back whole; hosted pages all remain
    # registered (check() proved that each step) and evict cleanly
    for rid in list(live):
        for pid in live.pop(rid):
            a.release(pid)
    a.check()
    assert a.pages_in_use == 0 and a.free_pages == 12
