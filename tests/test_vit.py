"""ViT family: model shapes/init, losses, metrics, transforms,
datasets, pos-embed interpolation, sharded equivalence, and an engine
training run on synthetic images."""

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlefleetx_tpu.models.vit import (
    CELoss, TopkAcc, ViT, ViTCELoss, ViTConfig, build_vision_model,
    interpolate_pos_embed,
)

TINY = ViTConfig(img_size=16, patch_size=4, class_num=5, embed_dim=32,
                 depth=2, num_heads=4)


def _params(model, x):
    return nn.meta.unbox(
        model.init({"params": jax.random.key(0)}, x))["params"]


def test_forward_shape_and_zero_head():
    x = jnp.ones((2, 16, 16, 3))
    model = ViT(TINY)
    params = _params(model, x)
    logits = model.apply({"params": params}, x)
    assert logits.shape == (2, 5)
    # zero-init classifier head -> exactly zero logits at init
    np.testing.assert_allclose(np.asarray(logits), 0.0)


def test_nchw_input_accepted():
    model = ViT(TINY)
    x_hwc = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 16, 16, 3)), jnp.float32)
    params = _params(model, x_hwc)
    a = model.apply({"params": params}, x_hwc)
    b = model.apply({"params": params},
                    jnp.transpose(x_hwc, (0, 3, 1, 2)))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_representation_head():
    cfg = ViTConfig(img_size=16, patch_size=4, class_num=5, embed_dim=32,
                    depth=1, num_heads=4, representation_size=16)
    x = jnp.ones((1, 16, 16, 3))
    model = ViT(cfg)
    params = _params(model, x)
    assert params["head0"]["kernel"].shape == (32, 16)
    # head bias init -10 (reference minus_tens_)
    np.testing.assert_allclose(np.asarray(params["head"]["bias"]), -10.0)


def test_zoo_names():
    m = build_vision_model({"name": "ViT_base_patch16_224",
                            "class_num": 10, "drop_rate": 0.1})
    assert m.config.embed_dim == 768 and m.config.qkv_bias
    with pytest.raises(ValueError):
        build_vision_model({"name": "ResNet5000"})


def test_zoo_mirrors_reference_builders():
    """Zoo entries must match the reference architectures exactly
    (reference vit.py:261-434): representation head on 224-res
    variants, epsilon=1e-6 + qkv_bias on base/large/g/G/6B, and the
    published mlp ratios — else checkpoints don't transfer."""
    expect = {
        "ViT_base_patch16_224": (768, 768, 1e-6, True, 4.0),
        "ViT_base_patch16_384": (768, None, 1e-6, True, 4.0),
        "ViT_large_patch16_224": (1024, 1024, 1e-6, True, 4.0),
        "ViT_large_patch32_384": (1024, None, 1e-6, True, 4.0),
        "ViT_huge_patch14_224": (1280, 1280, 1e-5, False, 4.0),
        "ViT_huge_patch14_384": (1280, None, 1e-5, False, 4.0),
        "ViT_g_patch14_224": (1408, 1408, 1e-6, True, 4.364),
        "ViT_G_patch14_224": (1664, 1664, 1e-6, True, 4.9231),
        "ViT_6B_patch14_224": (2320, 2320, 1e-6, True, 4.955),
    }
    for name, (dim, rep, eps, qkv, ratio) in expect.items():
        cfg = build_vision_model({"name": name}).config
        assert cfg.embed_dim == dim, name
        assert cfg.representation_size == rep, name
        assert cfg.epsilon == eps, name
        assert cfg.qkv_bias == qkv, name
        assert abs(cfg.mlp_ratio - ratio) < 1e-9, name


def test_celoss_matches_manual():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, 6)), jnp.float32)
    labels = jnp.asarray([0, 2, 4, 5])
    got = float(CELoss()(logits, labels))
    lp = jax.nn.log_softmax(logits, -1)
    want = -float(np.mean([lp[i, l] for i, l in enumerate(labels)]))
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # label smoothing lowers confidence target; still finite positive
    sm = float(CELoss(epsilon=0.1)(logits, labels))
    assert np.isfinite(sm) and sm > 0
    # soft labels accepted
    soft = jax.nn.one_hot(labels, 6)
    np.testing.assert_allclose(float(CELoss()(logits, soft)), want,
                               rtol=1e-6)


def test_vitceloss_sigmoid_bce():
    logits = jnp.asarray([[10.0, -10.0]], jnp.float32)
    labels = jnp.asarray([0])
    # nearly perfect prediction -> tiny loss; wrong label -> large
    good = float(ViTCELoss()(logits, labels))
    bad = float(ViTCELoss()(logits, jnp.asarray([1])))
    assert good < 1e-3 < bad


def test_topk_acc():
    logits = jnp.asarray([[0.1, 0.9, 0.0, 0.0],
                          [0.9, 0.1, 0.0, 0.0],
                          [0.0, 0.1, 0.2, 0.9]], jnp.float32)
    labels = jnp.asarray([1, 1, 2])
    # row 0: top1 = idx 1 (hit); row 1: top1 = idx 0 (miss), top2
    # {0, 1} (hit); row 2: top1 = idx 3 (miss), top2 {3, 2} (hit)
    out = TopkAcc(topk=[1, 2])(logits, labels)
    np.testing.assert_allclose(float(out["top1"]), 1 / 3, rtol=1e-6)
    np.testing.assert_allclose(float(out["top2"]), 3 / 3, rtol=1e-6)
    np.testing.assert_allclose(float(out["metric"]), float(out["top1"]))


def test_interpolate_pos_embed():
    pe = np.random.default_rng(2).normal(size=(1, 1 + 16, 8)) \
        .astype(np.float32)
    out = interpolate_pos_embed(pe, 64)
    assert out.shape == (1, 65, 8)
    np.testing.assert_allclose(out[:, 0], pe[:, 0])  # cls preserved
    assert interpolate_pos_embed(pe, 16) is pe  # no-op same size


def _write_images(tmp_path, n=24, classes=4, size=16):
    from PIL import Image
    rng = np.random.default_rng(3)
    root = tmp_path / "imgs"
    os.makedirs(root, exist_ok=True)
    lines = []
    for i in range(n):
        label = i % classes
        # class-dependent mean so the tiny model can learn
        arr = rng.normal(64 * label + 32, 10, (size, size, 3))
        arr = np.clip(arr, 0, 255).astype(np.uint8)
        fname = f"img_{i}.png"
        Image.fromarray(arr).save(root / fname)
        lines.append(f"{fname} {label}")
    list_path = tmp_path / "train_list.txt"
    list_path.write_text("\n".join(lines))
    return str(root), str(list_path)


TRANSFORM_OPS = [
    {"DecodeImage": {"to_rgb": True, "channel_first": False}},
    {"ResizeImage": {"resize_short": 16, "interpolation": "bicubic"}},
    {"CenterCropImage": {"size": 16}},
    {"NormalizeImage": {"scale": "1.0/255.0", "mean": [0.5, 0.5, 0.5],
                        "std": [0.5, 0.5, 0.5], "order": ""}},
    {"ToCHWImage": None},
]


def test_general_cls_dataset(tmp_path):
    from paddlefleetx_tpu.data.dataset.vision_dataset import (
        GeneralClsDataset,
    )
    root, list_path = _write_images(tmp_path)
    ds = GeneralClsDataset(root, list_path, transform_ops=TRANSFORM_OPS)
    img, label = ds[0]
    assert img.shape == (3, 16, 16) and img.dtype == np.float32
    assert -1.01 <= img.min() and img.max() <= 1.01
    assert len(ds) == 24 and label == 0


def test_random_transforms(tmp_path):
    from paddlefleetx_tpu.data.transforms import build_transforms
    ops = [
        {"DecodeImage": {}},
        {"RandCropImage": {"size": 8, "scale": [0.5, 1.0]}},
        {"RandFlipImage": {"flip_code": 1}},
        {"NormalizeImage": {}},
    ]
    t = build_transforms(ops)
    from PIL import Image
    import io
    arr = np.random.default_rng(4).integers(
        0, 255, (32, 32, 3)).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    out = t(buf.getvalue())
    assert out.shape == (8, 8, 3) and out.dtype == np.float32


def test_vit_trains_through_engine(tmp_path):
    """GeneralClsModule end-to-end: loss decreases, eval logs TopkAcc."""
    from paddlefleetx_tpu.core import Engine
    from paddlefleetx_tpu.data import build_dataloader
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import AttrDict, process_configs

    root, list_path = _write_images(tmp_path, n=64, classes=4)
    data_section = {
        "dataset": {
            "name": "GeneralClsDataset", "image_root": root,
            "cls_label_path": list_path, "class_num": 4,
            "transform_ops": TRANSFORM_OPS},
        "sampler": {"name": "DistributedBatchSampler",
                    "batch_size": 16, "shuffle": True,
                    "drop_last": True},
        "loader": {"num_workers": 1},
    }
    cfg = AttrDict({
        "Global": AttrDict({"device": "cpu", "seed": 2021,
                            "global_batch_size": None,
                            "local_batch_size": 2,
                            "micro_batch_size": 2}),
        "Engine": AttrDict({
            "max_steps": 16, "num_train_epochs": 4, "logging_freq": 4,
            "eval_freq": 1000, "eval_iters": 2,
            "mix_precision": AttrDict({}),
            "save_load": AttrDict({"save_steps": 1000,
                                   "output_dir": str(tmp_path / "out")}),
        }),
        "Model": AttrDict({
            "module": "GeneralClsModule",
            "model": AttrDict({"name": "ViT", "img_size": 16,
                               "patch_size": 4, "class_num": 4,
                               "embed_dim": 32, "depth": 2,
                               "num_heads": 4, "qkv_bias": True}),
            "loss": AttrDict({"train": AttrDict({"name": "CELoss"}),
                              "eval": AttrDict({"name": "CELoss"})}),
            "metric": AttrDict({
                "eval": AttrDict({"name": "TopkAcc", "topk": [1, 2]})}),
        }),
        "Distributed": AttrDict({"dp_degree": 8, "mp_degree": 1,
                                 "pp_degree": 1,
                                 "sharding": AttrDict({})}),
        "Optimizer": AttrDict({
            "name": "AdamW", "weight_decay": 0.0001,
            "lr": AttrDict({"name": "ViTLRScheduler",
                            "learning_rate": 0.003,
                            "decay_type": "cosine",
                            "warmup_steps": 2}),
            "grad_clip": AttrDict({"clip_norm": 1.0}),
        }),
        "Data": AttrDict({"Train": AttrDict(data_section),
                          "Eval": AttrDict(data_section)}),
    })
    process_configs(cfg, nranks=8)
    module = build_module(cfg)
    engine = Engine(cfg, module, mode="train")
    loader = build_dataloader(cfg.Data, "Train", num_replicas=1, rank=0)
    loader.batch_sampler.batch_size = cfg.Global.global_batch_size

    losses = []
    orig = module.training_step_end

    def capture(log):
        losses.append(log["loss"])
        orig(log)

    module.training_step_end = capture
    engine.fit(epoch=4, train_data_loader=loader)
    assert losses[-1] < losses[0], losses

    eval_loader = build_dataloader(cfg.Data, "Eval", num_replicas=1,
                                   rank=0)
    eval_loader.batch_sampler.batch_size = cfg.Global.global_batch_size
    engine.evaluate(epoch=0, valid_data_loader=eval_loader)
    assert "top1" in module.metrics and "best_metric" in module.metrics
    assert module.metrics["top1"] > 0.3  # learned something


def test_sharded_matches_single_device():
    from paddlefleetx_tpu.parallel import (
        TopologyConfig, build_mesh, make_sharding_rules,
    )
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(4, 16, 16, 3)), jnp.float32)
    model = ViT(TINY)
    params = _params(model, x)
    # non-trivial head so outputs differ from zero
    params = jax.tree.map(lambda p: p + 0.01, params)
    ref = model.apply({"params": params}, x)

    topo = TopologyConfig(dp_degree=2, mp_degree=2, sharding_degree=2,
                          sharding_stage=1)
    mesh = build_mesh(topo)
    rules = make_sharding_rules(topo)
    logical = nn.get_partition_spec(
        jax.eval_shape(model.init, {"params": jax.random.key(0)}, x))
    shardings = nn.logical_to_mesh_sharding(logical, mesh, list(rules))
    params_s = jax.device_put({"params": params},
                              nn.meta.unbox(shardings))["params"]
    with mesh, nn.logical_axis_rules(list(rules)):
        got = jax.jit(lambda p, i: model.apply({"params": p}, i))(
            params_s, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               atol=2e-5, rtol=1e-5)


def test_vit_fp16o2_config_runs_bf16_compute_fp32_params(tmp_path):
    """The fp16o2 recipe must actually run bf16 compute with fp32
    master params (VERDICT weak #4: the policy used to stop at the
    config)."""
    import os
    import jax
    import jax.numpy as jnp
    from paddlefleetx_tpu.models import build_module
    from paddlefleetx_tpu.utils.config import get_config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = get_config(
        os.path.join(repo, "configs/vis/vit/"
                           "ViT_base_patch16_224_pt_in1k_2n16c_dp_fp16o2.yaml"),
        overrides=["Model.model.name=ViT",
                   "Model.model.img_size=32",
                   "Model.model.patch_size=8",
                   "Model.model.embed_dim=32",
                   "Model.model.depth=1",
                   "Model.model.num_heads=2",
                   "Model.model.class_num=10"],
        nranks=8)
    assert cfg.Engine.mix_precision.use_pure_fp16 is True
    module = build_module(cfg)
    assert module.model.config.dtype == "bfloat16"   # policy reached model
    images = jnp.zeros((2, 3, 32, 32), jnp.float32)
    variables = module.model.init({"params": jax.random.key(0)}, images,
                                  deterministic=True)
    # fp32 master params
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32
    # bf16 compute: an inner block activation is bfloat16
    _, inter = module.model.apply(
        variables, images, deterministic=True,
        capture_intermediates=True)
    acts = [v for path, v in
            jax.tree_util.tree_flatten_with_path(
                inter["intermediates"])[0]
            if hasattr(v, "dtype") and "blocks" in str(path)]
    assert acts and any(a.dtype == jnp.bfloat16 for a in acts)


def test_colorjitter_pixels_randomerasing():
    """The augmentation tail ported in r4 (VERDICT #7; reference
    preprocess.py:295-378): semantics pinned per op."""
    import random as pyrandom

    from paddlefleetx_tpu.data.transforms import (
        ColorJitter, Pixels, RandomErasing,
    )

    img = np.random.default_rng(7).integers(
        0, 255, (24, 24, 3)).astype(np.uint8)

    # zero-strength jitter is the identity (no op selected)
    same = ColorJitter()(img)
    np.testing.assert_array_equal(same, img)
    # nonzero jitter changes the image but keeps shape/dtype/range
    pyrandom.seed(3)
    out = ColorJitter(brightness=0.6, contrast=0.6, saturation=0.6,
                      hue=0.2)(img)
    assert out.shape == img.shape and out.dtype == np.uint8
    assert not np.array_equal(out, img)
    with pytest.raises(ValueError):
        ColorJitter(hue=0.9)

    # Pixels modes: const -> configured mean; rand -> one RGB value;
    # pixel -> full patch
    assert np.allclose(Pixels("const", [1, 2, 3])(4, 5, 3), [1, 2, 3])
    assert Pixels("rand")(4, 5, 3).shape == (1, 1, 3)
    assert Pixels("pixel")(4, 5, 3).shape == (4, 5, 3)
    with pytest.raises(ValueError):
        Pixels("nope")

    # RandomErasing: EPSILON=0 never erases; EPSILON=1 replaces one
    # rectangle with the const mean and never mutates its input
    f = img.astype(np.float32)
    np.testing.assert_array_equal(RandomErasing(EPSILON=0.0)(f), f)
    pyrandom.seed(11)
    fill = 7.5
    erased = RandomErasing(EPSILON="1.0", mean=[fill] * 3,
                           use_log_aspect=True)(f)
    assert erased.shape == f.shape
    changed = (erased != f).any(axis=-1)
    assert changed.any(), "EPSILON=1 must erase a rectangle"
    assert (erased[changed] == fill).all()
    assert not np.array_equal(erased, f) and (f == img).all(), \
        "input must not be mutated"
    # erased region is one solid rectangle
    rows = np.flatnonzero(changed.any(1))
    cols = np.flatnonzero(changed.any(0))
    assert changed[rows[0]:rows[-1] + 1, cols[0]:cols[-1] + 1].all()


def test_reference_augmentation_config_resolves(tmp_path):
    """Every transform name the reference's ViT recipes use — plus the
    augmentation-heavy tail — resolves through build_transforms and
    runs end-to-end (VERDICT r3 #7 done-criterion)."""
    from paddlefleetx_tpu.data.transforms import build_transforms
    ops = [
        {"DecodeImage": {"to_rgb": True, "channel_first": False}},
        {"RandCropImage": {"size": 16, "scale": [0.05, 1.0],
                           "interpolation": "bicubic",
                           "backend": "pil"}},
        {"RandFlipImage": {"flip_code": 1}},
        {"ColorJitter": {"brightness": 0.4, "contrast": 0.4,
                         "saturation": 0.4, "hue": 0.1}},
        {"NormalizeImage": {"scale": "1.0/255.0",
                            "mean": [0.485, 0.456, 0.406],
                            "std": [0.229, 0.224, 0.225],
                            "order": ""}},
        {"RandomErasing": {"EPSILON": 1.0, "sl": 0.02, "sh": 0.4,
                           "r1": 0.3, "mode": "pixel"}},
        {"ToCHWImage": {}},
    ]
    t = build_transforms(ops)
    import io

    from PIL import Image
    arr = np.random.default_rng(5).integers(
        0, 255, (32, 32, 3)).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    out = t(buf.getvalue())
    assert out.shape == (3, 16, 16) and out.dtype == np.float32
