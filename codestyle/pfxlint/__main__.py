"""Console entry: ``python -m codestyle.pfxlint [paths] [options]``.

Run from the repo root. With no paths, lints the full tree (the CI
gate); with paths, restricts file-scoped rules to those files while
tree-scoped contract rules still see the whole tree they need.

Options:
    --select CODES        comma-separated rule ids to run exclusively
    --ignore CODES        comma-separated rule ids to drop
    --baseline FILE       baseline path (default
                          codestyle/pfxlint/baseline.txt)
    --no-baseline         report baselined findings too
    --write-baseline      rewrite the baseline from current findings
    --format FMT          output format: ``text`` (default) or
                          ``github`` (Actions ``::error`` annotations
                          that render inline on PRs)
    --list-rules          print rule ids and exit
    --stats               print reachability/suppression statistics,
                          including per-rule inline-suppression counts
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional


def _usage(msg: str) -> int:
    print(f"pfxlint: {msg}", file=sys.stderr)
    print(__doc__, file=sys.stderr)
    return 2


def main(argv: Optional[List[str]] = None) -> int:
    """CLI driver; returns the process exit code.

    Args:
        argv (list): argument vector without the program name; None
            reads ``sys.argv[1:]``.

    Returns:
        0 clean, 1 unbaselined findings, 2 usage error.
    """
    from . import engine
    from .rules import rule_codes

    args = list(sys.argv[1:] if argv is None else argv)
    root = os.getcwd()
    select = ignore = None
    baseline_path = None
    use_baseline = True
    write_baseline = False
    stats = False
    fmt = "text"
    paths: List[str] = []

    known = set(rule_codes())
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--list-rules":
            print("\n".join(rule_codes()))
            return 0
        if a in ("--select", "--ignore", "--baseline", "--root",
                 "--format"):
            if i + 1 >= len(args):
                return _usage(f"{a} needs a value")
            val = args[i + 1]
            if a == "--select":
                select = {c.strip() for c in val.split(",") if c.strip()}
                bad = select - known
                if bad:
                    return _usage(f"unknown rule id(s): {sorted(bad)}")
            elif a == "--ignore":
                ignore = {c.strip() for c in val.split(",") if c.strip()}
                bad = ignore - known
                if bad:
                    return _usage(f"unknown rule id(s): {sorted(bad)}")
            elif a == "--baseline":
                baseline_path = val
            elif a == "--format":
                if val not in ("text", "github"):
                    return _usage(f"unknown format {val!r}")
                fmt = val
            else:
                root = val
            i += 2
            continue
        if a == "--no-baseline":
            use_baseline = False
        elif a == "--write-baseline":
            write_baseline = True
        elif a == "--stats":
            stats = True
        elif a.startswith("-"):
            return _usage(f"unknown option {a!r}")
        else:
            paths.append(a)
        i += 1

    if not os.path.isdir(os.path.join(root, "codestyle")):
        return _usage(
            f"run from the repo root (no codestyle/ under {root!r})")

    try:
        result = engine.run_lint(
            root, paths=paths or None, select=select, ignore=ignore,
            baseline_path=baseline_path, use_baseline=use_baseline)
    except SyntaxError as e:
        print(f"pfxlint: cannot parse {e.filename}:{e.lineno}: "
              f"{e.msg}", file=sys.stderr)
        return 2

    if write_baseline:
        path = baseline_path or os.path.join(
            root, "codestyle", "pfxlint", "baseline.txt")
        engine.write_baseline(path, result.findings + result.baselined)
        print(f"pfxlint: wrote {len(result.findings) + len(result.baselined)}"
              f" fingerprints to {path}")
        return 0

    for f in result.findings:
        if fmt == "github":
            # one annotation per finding; message must stay one line
            msg = f.message.replace("\n", " ")
            print(f"::error file={f.path},line={f.line},"
                  f"title={f.code}::{msg}")
        else:
            print(f)
    if result.unused_baseline:
        print(f"pfxlint: note: {len(result.unused_baseline)} stale "
              f"baseline fingerprint(s) no longer fire — prune them:",
              file=sys.stderr)
        for fp in result.unused_baseline:
            print(f"  {fp}", file=sys.stderr)
    if stats:
        print(f"pfxlint: {len(result.findings)} finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{len(result.suppressed)} suppressed inline",
              file=sys.stderr)
        for code, n in sorted(result.suppression_counts().items()):
            print(f"pfxlint: suppressed[{code}]={n}", file=sys.stderr)
    if result.findings:
        print(f"pfxlint: {len(result.findings)} finding(s) "
              f"(suppress inline with '# pfxlint: disable=ID' or "
              f"carry in the baseline — docs/static_analysis.md)",
              file=sys.stderr)
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
