"""Thread-entry graph + lock-scope analysis for the PFX3xx rules.

The jit call graph (``callgraph.py``) answers "can this run under a
trace?". This module answers the concurrency twin: "can this run on a
non-main thread, and which locks are held when it touches shared
state?". It is built once per lint run from the same parsed ASTs.

Thread roots
    - ``threading.Thread(target=...)`` / ``threading.Timer(_, fn)``
      targets — resolved through bare names, ``self.method`` bound
      methods, attributes holding callbacks, and lambdas (the calls
      inside a lambda target become roots themselves);
    - every method of an in-tree ``BaseHTTPRequestHandler`` /
      ``socketserver`` handler subclass (``ThreadingHTTPServer`` runs
      each request on its own thread).

Reachability
    BFS from the roots along resolved call edges. Resolution goes
    beyond the jit graph's: a light type-inference fixpoint tracks
    which in-tree class each attribute / global / parameter / local /
    return value can hold (constructor calls, annotations including
    ``Optional[C]`` / ``List[C]`` element types, call-site argument
    flow), so ``self._recorder.emit(...)`` resolves through
    ``self._recorder = FlightRecorder(...)`` three calls away, and a
    callback-flow pass tracks function references through the same
    channels, so ``health=self._health_state`` stored by
    ``MetricsServer.set_health`` marks ``_health_state`` as running on
    the HTTP threads that invoke ``self._health()``. ``@property``
    getters are call edges on attribute reads. Functions with no
    in-tree caller that are not thread roots seed the ``main``
    context.

Lock scopes
    Intraprocedurally per function: ``with self._lock:`` blocks,
    bare ``lk.acquire()`` .. ``lk.release()`` regions (including the
    ``try/finally`` idiom). Locks are identified by where they live
    (``Class._lock`` attribute, module global, function local) —
    instance identity is abstracted away, which is sound for the
    one-lock-per-object idiom this repo uses. Helpers only ever
    called with a lock held inherit it: the effective lock set of a
    function is its local set plus the INTERSECTION over all in-tree
    call sites of the locks held there (a meet-over-callers fixpoint;
    thread roots and callback-invoked functions contribute the empty
    set, since something outside the scanned tree can call them
    bare).

Known-unsound patterns (documented in docs/static_analysis.md):
    - object-graph aliasing: a list handed out by a method and mutated
      through the alias is invisible (accesses are tracked per
      attribute/global, not per object);
    - ``ProcessPoolExecutor.submit`` targets are NOT thread roots on
      purpose — separate processes share no memory;
    - two threads spawned from the SAME target function merge into
      one context, so a function racing only with itself on a global
      is missed unless some other context also touches the state;
    - element types of containers filled outside ``append`` / literal
      / annotation forms are unknown, so calls through them do not
      resolve.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, ModuleIndex, _dotted_from

#: constructors that define a lock object (leaf name after resolution)
_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}

#: constructors of internally-synchronized objects — mutating these
#: through their own methods (Event.set/clear, Queue.put/get) is safe
#: from any thread, so their state keys are exempt from PFX301 the
#: same way lock objects are (REBINDING one is still an object-
#: identity swap the analysis deliberately ignores — documented
#: known-unsound in docs/static_analysis.md)
_THREADSAFE_FACTORIES = {
    "threading.Event": "Event",
    "queue.Queue": "Queue",
    "queue.SimpleQueue": "Queue",
    "queue.LifoQueue": "Queue",
    "queue.PriorityQueue": "Queue",
    # deque append/popleft/iteration-copy are single GIL-atomic C
    # calls (CPython documents deques as thread-safe for these); the
    # timeline ring buffers (observability/timeline.py) ride exactly
    # this, writer-appends racing snapshot copies without a lock
    "collections.deque": "Deque",
}

#: thread-spawning callables whose function argument runs off-main
_THREAD_FACTORIES = {"threading.Thread", "threading.Timer"}

#: stdlib handler base classes whose methods run per-request threads
_HANDLER_BASES = {
    "http.server.BaseHTTPRequestHandler",
    "http.server.SimpleHTTPRequestHandler",
    "socketserver.BaseRequestHandler",
    "socketserver.StreamRequestHandler",
    "socketserver.DatagramRequestHandler",
}

#: method names that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse", "put", "put_nowait",
}

#: typing wrappers whose subscript passes the inner type through
_ANN_PASSTHROUGH = {"Optional", "Union", "Final", "ClassVar",
                    "Annotated"}
#: typing containers whose subscript names the ELEMENT type
_ANN_CONTAINERS = {"List", "list", "Sequence", "Set", "set",
                   "FrozenSet", "Tuple", "tuple", "Iterable",
                   "Iterator", "Deque", "deque"}
#: typing mappings whose VALUE slot names the element type
_ANN_MAPPINGS = {"Dict", "dict", "Mapping", "MutableMapping",
                 "DefaultDict", "OrderedDict"}

#: constructor/init-ish methods whose own-attribute writes happen
#: before any thread can observe the object
_INIT_METHODS = {"__init__", "__post_init__", "__new__"}


@dataclasses.dataclass
class Access:
    """One read or write of a tracked shared-state location."""

    key: str               # "mod:Class.attr" or "mod:NAME" (global)
    display: str           # short human name ("Class.attr")
    fn: FunctionInfo
    write: bool
    lineno: int
    locks: FrozenSet[str]  # effective lock keys held (incl. inherited)
    in_init: bool          # happens-before any thread start


@dataclasses.dataclass
class CallOp:
    """One call site, with the locks held around it."""

    fn: FunctionInfo
    node: Optional[ast.Call]    # None for synthesized property reads
    gdot: Optional[str]         # resolved global dotted name, if any
    attr: Optional[str]         # method name when func is Attribute
    n_pos: int                  # positional argument count
    targets: Tuple[str, ...]    # resolved in-tree callee qualnames
    lineno: int
    locks: FrozenSet[str]       # effective lock keys held


@dataclasses.dataclass
class Acquisition:
    """One lock acquisition with the locks already held there."""

    fn: FunctionInfo
    lock: str
    held: FrozenSet[str]        # effective locks held at acquire time
    lineno: int


class ThreadGraph:
    """The built artifact rules consume; see module docstring."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        #: qualname -> set of context labels ("main", "thread:<qual>",
        #: "http:<class key>")
        self.contexts: Dict[str, Set[str]] = {}
        #: root qualname -> context label it anchors
        self.thread_roots: Dict[str, str] = {}
        #: lock key -> factory leaf name ("Lock", "RLock", ...)
        self.lock_kinds: Dict[str, str] = {}
        #: state key -> kind for internally-synchronized objects
        #: (Event, Queue); exempt from race tracking, NOT lockable
        self.safe_kinds: Dict[str, str] = {}
        self.accesses: List[Access] = []
        self.calls: List[CallOp] = []
        self.acquisitions: List[Acquisition] = []
        #: inference maps, keyed ("attr", class_key, name) /
        #: ("glob", mod, name) / ("param", fnqual, name) /
        #: ("local", fnqual, name) / ("ret", fnqual)
        self.types: Dict[Tuple, Set[str]] = {}
        self.elems: Dict[Tuple, Set[str]] = {}
        self.fnrefs: Dict[Tuple, Set[str]] = {}
        #: (class_key, attr) -> getter qualname for @property methods
        self.properties: Dict[Tuple[str, str], str] = {}
        self._module_globals: Dict[str, Set[str]] = {}
        self._edges_cache: Dict[str, Set[str]] = {}
        self._build()

    # -- public lookups -------------------------------------------------
    def contexts_of(self, qualname: str) -> Set[str]:
        """Thread contexts a function can run on (``{"main"}`` for
        anything the analysis could not place — conservative: a lone
        context produces no cross-thread findings)."""
        return self.contexts.get(qualname) or {"main"}

    def accesses_for(self, key: str) -> List[Access]:
        return [a for a in self.accesses if a.key == key]

    # -- construction ---------------------------------------------------
    def _build(self):
        for m in self.graph.modules.values():
            self._module_globals[m.modname] = _module_assigned_names(
                m.tree)
        self._collect_properties()
        self._infer_fixpoint()
        self._collect_locks()
        self._walk_all_functions()
        self._find_thread_roots()
        self._propagate_contexts()
        self._inherit_caller_locks()

    def _collect_properties(self):
        for m in self.graph.modules.values():
            for qual, info in m.functions.items():
                if not info.class_name:
                    continue
                for deco in getattr(info.node, "decorator_list", []):
                    d = _dotted_from(deco)
                    if d in ("property", "functools.cached_property",
                             "cached_property"):
                        ck = f"{m.modname}:{info.class_name}"
                        self.properties[(ck, info.node.name)] = \
                            info.qualname

    # -- type / callback inference --------------------------------------
    def _infer_fixpoint(self):
        for m in self.graph.modules.values():
            self._infer_class_fields(m)
        for _ in range(10):
            before = (sum(len(v) for v in self.types.values()),
                      sum(len(v) for v in self.elems.values()),
                      sum(len(v) for v in self.fnrefs.values()))
            for m in self.graph.modules.values():
                self._infer_module_level(m)
                for info in m.functions.values():
                    self._infer_function(m, info)
            after = (sum(len(v) for v in self.types.values()),
                     sum(len(v) for v in self.elems.values()),
                     sum(len(v) for v in self.fnrefs.values()))
            if after == before:
                break

    def _infer_class_fields(self, m: ModuleIndex):
        """Class-body ``AnnAssign`` fields (dataclass fields, class
        attributes) seed attribute types/element types once."""

        def walk(body, scope: List[str]):
            """Collect annotated class-body fields, tracking the
            qualname scope the ModuleIndex convention uses."""
            for st in body:
                if isinstance(st, ast.ClassDef):
                    cq = ".".join(scope + [st.name])
                    ck = f"{m.modname}:{cq}"
                    for f in st.body:
                        if isinstance(f, ast.AnnAssign) and \
                                isinstance(f.target, ast.Name):
                            t, e = self._ann_types(m, f.annotation)
                            self._grow(self.types,
                                       ("attr", ck, f.target.id), t)
                            self._grow(self.elems,
                                       ("attr", ck, f.target.id), e)
                    walk(st.body, scope + [st.name])
                elif isinstance(st, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    walk(st.body, scope + [st.name + ".<locals>"])

        walk(m.tree.body, [])

    def _infer_module_level(self, m: ModuleIndex):
        for st in m.tree.body:
            if isinstance(st, (ast.Assign, ast.AnnAssign)):
                self._infer_assign(m, None, st)

    def _infer_function(self, m: ModuleIndex, fn: FunctionInfo):
        # annotations seed param types
        for p, ann in fn.annotations.items():
            if ann is not None:
                t, e = self._ann_types(m, ann)
                self._grow(self.types, ("param", fn.qualname, p), t)
                self._grow(self.elems, ("param", fn.qualname, p), e)
        gl = _global_decls(fn.node)
        for st in _own_statements(fn.node):
            if isinstance(st, (ast.Assign, ast.AnnAssign)):
                self._infer_assign(m, fn, st, gl)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                if isinstance(st.target, ast.Name):
                    self._grow(
                        self.types, self._name_dest(fn, st.target.id, gl),
                        self._elems_of(fn, st.iter))
            elif isinstance(st, ast.Return) and st.value is not None:
                self._grow(self.types, ("ret", fn.qualname),
                           self._types_of(fn, st.value))
                self._grow(self.elems, ("ret", fn.qualname),
                           self._elems_of(fn, st.value))
                self._grow(self.fnrefs, ("ret", fn.qualname),
                           self._fnrefs_of(fn, st.value))
            # call-site argument flow into callee params
            for node in ast.walk(st):
                if isinstance(node, ast.Call):
                    self._infer_call(m, fn, node)

    def _infer_assign(self, m: ModuleIndex, fn: Optional[FunctionInfo],
                      st, gl: Set[str] = frozenset()):
        value = st.value
        targets = st.targets if isinstance(st, ast.Assign) else \
            [st.target]
        ann = getattr(st, "annotation", None)
        ann_t: Set[str] = set()
        ann_e: Set[str] = set()
        if ann is not None:
            ann_t, ann_e = self._ann_types(m, ann)
        v_t = self._types_of(fn, value, m) if value is not None else set()
        v_e = self._elems_of(fn, value, m) if value is not None else set()
        v_f = self._fnrefs_of(fn, value, m) if value is not None else set()
        for tgt in targets:
            # tuple unpack: match elementwise when the RHS is a tuple
            if isinstance(tgt, ast.Tuple) and \
                    isinstance(value, ast.Tuple) and \
                    len(tgt.elts) == len(value.elts):
                for te, ve in zip(tgt.elts, value.elts):
                    fake = ast.Assign(targets=[te], value=ve)
                    self._infer_assign(m, fn, fake, gl)
                continue
            dest = self._dest_key(m, fn, tgt, gl)
            if dest is None:
                # subscript store feeds the container's element types
                if isinstance(tgt, ast.Subscript):
                    ek = self._expr_key_dest(m, fn, tgt.value, gl)
                    if ek is not None:
                        self._grow(self.elems, ek, v_t)
                continue
            self._grow(self.types, dest, v_t | ann_t)
            self._grow(self.elems, dest, v_e | ann_e)
            self._grow(self.fnrefs, dest, v_f)

    def _infer_call(self, m: ModuleIndex, fn: FunctionInfo,
                    call: ast.Call):
        targets = self.resolve_call(fn, call)
        for tq in targets:
            tinfo = self.graph.functions.get(tq)
            if tinfo is None:
                continue
            params = [p for p in tinfo.params if p not in ("self", "cls")]
            bound_as_method = isinstance(call.func, ast.Attribute) or \
                tinfo.node.name == "__init__"
            plist = params if bound_as_method else \
                [p for p in tinfo.params]
            # positional
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred) or i >= len(plist):
                    break
                self._bind_param(fn, tinfo, plist[i], arg)
            # keywords
            for kw in call.keywords:
                if kw.arg and kw.arg in tinfo.params:
                    self._bind_param(fn, tinfo, kw.arg, kw.value)

    def _bind_param(self, fn: FunctionInfo, target: FunctionInfo,
                    pname: str, arg: ast.AST):
        self._grow(self.types, ("param", target.qualname, pname),
                   self._types_of(fn, arg))
        self._grow(self.elems, ("param", target.qualname, pname),
                   self._elems_of(fn, arg))
        self._grow(self.fnrefs, ("param", target.qualname, pname),
                   self._fnrefs_of(fn, arg))

    def _ann_types(self, m: ModuleIndex,
                   ann: Optional[ast.AST]
                   ) -> Tuple[Set[str], Set[str]]:
        """Annotation AST -> (in-tree class types, element types).
        Understands ``Optional[C]`` / ``Union`` passthrough,
        ``List[C]``-style containers, and ``Dict[K, C]`` values."""
        if ann is None:
            return set(), set()
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return set(), set()
        if isinstance(ann, ast.Subscript):
            base = _dotted_from(ann.value)
            leaf = base.split(".")[-1] if base else ""
            inner = ann.slice
            if leaf in _ANN_PASSTHROUGH:
                if isinstance(inner, ast.Tuple):
                    t: Set[str] = set()
                    e: Set[str] = set()
                    for el in inner.elts:
                        it, ie = self._ann_types(m, el)
                        t |= it
                        e |= ie
                    return t, e
                return self._ann_types(m, inner)
            if leaf in _ANN_CONTAINERS:
                elts = inner.elts if isinstance(inner, ast.Tuple) \
                    else [inner]
                e = set()
                for el in elts:
                    e |= self._ann_types(m, el)[0]
                return set(), e
            if leaf in _ANN_MAPPINGS and isinstance(inner, ast.Tuple) \
                    and len(inner.elts) == 2:
                return set(), self._ann_types(m, inner.elts[1])[0]
            return set(), set()
        dotted = _dotted_from(ann)
        if dotted is None or dotted == "None":
            return set(), set()
        ck = self.graph._class_key(m, self.graph.resolve_dotted(
            m, dotted))
        return ({ck} if ck else set()), set()

    @staticmethod
    def _grow(table: Dict[Tuple, Set[str]], key: Tuple,
              vals: Set[str]):
        if vals:
            table.setdefault(key, set()).update(vals)

    def _name_dest(self, fn: FunctionInfo, name: str,
                   gl: Set[str]) -> Tuple:
        if name in gl:
            return ("glob", fn.modname, name)
        return ("local", fn.qualname, name)

    def _dest_key(self, m: ModuleIndex, fn: Optional[FunctionInfo],
                  tgt: ast.AST, gl: Set[str]) -> Optional[Tuple]:
        if isinstance(tgt, ast.Name):
            if fn is None:
                return ("glob", m.modname, tgt.id)
            return self._name_dest(fn, tgt.id, gl)
        if isinstance(tgt, ast.Attribute) and fn is not None:
            for ck in self._self_types(fn, tgt.value):
                return ("attr", ck, tgt.attr)
        return None

    def _expr_key_dest(self, m: ModuleIndex,
                       fn: Optional[FunctionInfo], expr: ast.AST,
                       gl: Set[str]) -> Optional[Tuple]:
        """Key of a container-valued expr for element-type feeding."""
        return self._dest_key(m, fn, expr, gl)

    def _self_types(self, fn: FunctionInfo,
                    expr: ast.AST) -> List[str]:
        """Class keys an attribute RECEIVER can hold (``self`` / typed
        expr), ordered deterministically."""
        if isinstance(expr, ast.Name) and expr.id in ("self", "cls") \
                and fn.class_name:
            return [f"{fn.modname}:{fn.class_name}"]
        return sorted(self._types_of(fn, expr))

    # -- expression evaluation ------------------------------------------
    def _types_of(self, fn: Optional[FunctionInfo], expr: ast.AST,
                  m: Optional[ModuleIndex] = None) -> Set[str]:
        if expr is None:
            return set()
        mod = m or (self.graph.modules.get(fn.modname) if fn else None)
        if isinstance(expr, ast.Call):
            out: Set[str] = set()
            dotted = _dotted_from(expr.func)
            if dotted is not None and mod is not None:
                gdot = self.graph.resolve_dotted(mod, dotted)
                ck = self.graph._class_key(mod, gdot)
                if ck:
                    return {ck}
            for tq in self.resolve_call(fn, expr) if fn else ():
                out |= self.types.get(("ret", tq), set())
            return out
        if isinstance(expr, ast.Name):
            if expr.id in ("self", "cls") and fn and fn.class_name:
                return {f"{fn.modname}:{fn.class_name}"}
            return self._lookup_name(fn, expr.id, self.types)
        if isinstance(expr, ast.Attribute):
            out = set()
            if fn is not None:
                for ck in self._self_types(fn, expr.value):
                    out |= self.types.get(("attr", ck, expr.attr),
                                          set())
            return out
        if isinstance(expr, ast.Subscript):
            return self._elems_of(fn, expr.value, m)
        if isinstance(expr, ast.IfExp):
            return self._types_of(fn, expr.body, m) | \
                self._types_of(fn, expr.orelse, m)
        if isinstance(expr, ast.BoolOp):
            out = set()
            for v in expr.values:
                out |= self._types_of(fn, v, m)
            return out
        if isinstance(expr, ast.Await):
            return self._types_of(fn, expr.value, m)
        if isinstance(expr, ast.NamedExpr):
            return self._types_of(fn, expr.value, m)
        return set()

    def _elems_of(self, fn: Optional[FunctionInfo], expr: ast.AST,
                  m: Optional[ModuleIndex] = None) -> Set[str]:
        if expr is None:
            return set()
        if isinstance(expr, (ast.List, ast.Tuple, ast.Set)):
            out: Set[str] = set()
            for e in expr.elts:
                out |= self._types_of(fn, e, m)
            return out
        if isinstance(expr, ast.ListComp):
            return self._types_of(fn, expr.elt, m)
        if isinstance(expr, ast.Name):
            return self._lookup_name(fn, expr.id, self.elems)
        if isinstance(expr, ast.Attribute) and fn is not None:
            out = set()
            for ck in self._self_types(fn, expr.value):
                out |= self.elems.get(("attr", ck, expr.attr), set())
            return out
        if isinstance(expr, ast.Call) and fn is not None:
            out = set()
            for tq in self.resolve_call(fn, expr):
                out |= self.elems.get(("ret", tq), set())
            return out
        if isinstance(expr, ast.IfExp):
            return self._elems_of(fn, expr.body, m) | \
                self._elems_of(fn, expr.orelse, m)
        if isinstance(expr, ast.BoolOp):
            out = set()
            for v in expr.values:
                out |= self._elems_of(fn, v, m)
            return out
        return set()

    def _fnrefs_of(self, fn: Optional[FunctionInfo], expr: ast.AST,
                   m: Optional[ModuleIndex] = None) -> Set[str]:
        if expr is None:
            return set()
        mod = m or (self.graph.modules.get(fn.modname) if fn else None)
        if isinstance(expr, (ast.IfExp,)):
            return self._fnrefs_of(fn, expr.body, m) | \
                self._fnrefs_of(fn, expr.orelse, m)
        if isinstance(expr, ast.BoolOp):
            out: Set[str] = set()
            for v in expr.values:
                out |= self._fnrefs_of(fn, v, m)
            return out
        if isinstance(expr, (ast.Name, ast.Attribute)):
            # a direct function/method reference first
            if mod is not None:
                hit = self.graph._resolve_fn_arg(mod, fn, expr)
                if hit is not None:
                    return {hit.qualname}
            if isinstance(expr, ast.Name):
                return self._lookup_name(fn, expr.id, self.fnrefs)
            if isinstance(expr, ast.Attribute) and fn is not None:
                out = set()
                for ck in self._self_types(fn, expr.value):
                    out |= self.fnrefs.get(("attr", ck, expr.attr),
                                           set())
                return out
        if isinstance(expr, ast.Call) and fn is not None:
            # functools.partial(f, ...) and friends: first arg
            dotted = _dotted_from(expr.func)
            if dotted and mod is not None:
                gdot = self.graph.resolve_dotted(mod, dotted)
                if gdot in ("functools.partial", "partial") and \
                        expr.args:
                    return self._fnrefs_of(fn, expr.args[0], m)
            out = set()
            for tq in self.resolve_call(fn, expr):
                out |= self.fnrefs.get(("ret", tq), set())
            return out
        return set()

    def _lookup_name(self, fn: Optional[FunctionInfo], name: str,
                     table: Dict[Tuple, Set[str]]) -> Set[str]:
        """Name lookup through local -> param -> enclosing-function
        locals (the ``outer = self`` closure idiom) -> module
        global."""
        if fn is None:
            return set()
        out = table.get(("local", fn.qualname, name), set()) | \
            table.get(("param", fn.qualname, name), set())
        if out:
            return set(out)
        for enc in _enclosing_function_quals(fn.qualname):
            hit = table.get(("local", enc, name), set()) | \
                table.get(("param", enc, name), set())
            if hit:
                return set(hit)
        return set(table.get(("glob", fn.modname, name), set()))

    # -- call resolution ------------------------------------------------
    def resolve_call(self, fn: Optional[FunctionInfo],
                     call: ast.Call) -> List[str]:
        """In-tree callee qualnames a call site can land on (possibly
        several through callback sets; empty when external)."""
        if fn is None:
            return []
        mod = self.graph.modules.get(fn.modname)
        if mod is None:
            return []
        dotted = _dotted_from(call.func)
        if dotted is not None:
            gdot = self.graph.resolve_dotted(mod, dotted)
            ck = self.graph._class_key(mod, gdot)
            if ck:
                cmod, cqual = ck.split(":", 1)
                init = self.graph._method_on(
                    self.graph.modules[cmod], cqual, "__init__")
                return [init.qualname] if init else []
            hit = self.graph._resolve_fn_arg(mod, fn, call.func)
            if hit is not None:
                return [hit.qualname]
        if isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            out: Set[str] = set()
            for ck in self._self_types(fn, call.func.value):
                cmod, cqual = ck.split(":", 1)
                m = self.graph.modules.get(cmod)
                if m is None:
                    continue
                hit = self.graph._method_on(m, cqual, meth)
                if hit is not None:
                    out.add(hit.qualname)
                else:
                    # a stored callback invoked through an attribute
                    out |= self.fnrefs.get(("attr", ck, meth), set())
            return sorted(out)
        if isinstance(call.func, ast.Name):
            refs = self._lookup_name(fn, call.func.id, self.fnrefs)
            if refs:
                return sorted(refs)
        return []

    # -- locks ----------------------------------------------------------
    def _collect_locks(self):
        """Register every attribute/global/local assigned from a
        ``threading.Lock()``-family constructor."""
        for m in self.graph.modules.values():
            for st in m.tree.body:
                self._lock_from_assign(m, None, st, frozenset())
            for fn in m.functions.values():
                gl = _global_decls(fn.node)
                for st in _own_statements(fn.node):
                    self._lock_from_assign(m, fn, st, gl)

    def _lock_from_assign(self, m: ModuleIndex,
                          fn: Optional[FunctionInfo], st,
                          gl: Set[str]):
        if not isinstance(st, (ast.Assign, ast.AnnAssign)):
            return
        value = st.value
        kind = self._lock_kind(m, value)
        table = self.lock_kinds
        if kind is None:
            kind = self._safe_kind(m, value)
            table = self.safe_kinds
        if kind is None:
            return
        targets = st.targets if isinstance(st, ast.Assign) else \
            [st.target]
        for tgt in targets:
            dest = self._dest_key(m, fn, tgt, gl)
            if dest is None:
                continue
            table[_state_key(dest)] = kind

    def _lock_kind(self, m: ModuleIndex,
                   value: Optional[ast.AST]) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        dotted = _dotted_from(value.func)
        if dotted is None:
            return None
        return _LOCK_FACTORIES.get(self.graph.resolve_dotted(m, dotted))

    def _safe_kind(self, m: ModuleIndex,
                   value: Optional[ast.AST]) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        dotted = _dotted_from(value.func)
        if dotted is None:
            return None
        return _THREADSAFE_FACTORIES.get(
            self.graph.resolve_dotted(m, dotted))

    def _lock_key_of(self, env: "_WalkEnv",
                     expr: ast.AST) -> Optional[str]:
        """The registered lock key an expression denotes, if any."""
        key = self._access_key(env.fn, expr, env)
        if key is not None and key[0] in self.lock_kinds:
            return key[0]
        # function-local lock objects (rare but cheap to honor)
        if isinstance(expr, ast.Name):
            local_key = f"{env.fn.qualname}.{expr.id}"
            if local_key in self.lock_kinds:
                return local_key
        return None

    # -- per-function walk ----------------------------------------------
    def _walk_all_functions(self):
        for m in self.graph.modules.values():
            for fn in m.functions.values():
                self._walk_fn(fn)

    def _walk_fn(self, fn: FunctionInfo):
        gl = _global_decls(fn.node)
        locals_ = _local_names(fn.node, gl) | set(fn.params)
        in_init = fn.node.name in _INIT_METHODS and \
            fn.class_name is not None
        env = _WalkEnv(fn, gl, locals_, in_init)
        self._walk_block(list(getattr(fn.node, "body", [])), [], env)

    def _walk_block(self, stmts: Sequence[ast.stmt],
                    held: List[str], env: "_WalkEnv"):
        held = list(held)
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                entered: List[str] = []
                for item in st.items:
                    lk = self._lock_key_of(env, item.context_expr)
                    if lk is not None:
                        self._record_acquire(env, lk, held + entered,
                                             item.context_expr.lineno)
                        entered.append(lk)
                    else:
                        self._collect(item.context_expr,
                                      held + entered, env)
                self._walk_block(st.body, held + entered, env)
                continue
            acq = self._acquire_release(env, st)
            if acq is not None:
                lk, is_acquire = acq
                if is_acquire:
                    self._record_acquire(env, lk, held, st.lineno)
                    held.append(lk)
                elif lk in held:
                    held.remove(lk)
                continue
            if isinstance(st, ast.Try):
                self._walk_block(st.body, held, env)
                for h in st.handlers:
                    self._walk_block(h.body, held, env)
                self._walk_block(st.orelse, held, env)
                self._walk_block(st.finalbody, held, env)
                # l.acquire(); try: ... finally: l.release() — the
                # release in finalbody ends the region after the Try
                for rel in self._releases_in(env, st.finalbody):
                    if rel in held:
                        held.remove(rel)
                continue
            if isinstance(st, (ast.If,)):
                self._collect(st.test, held, env)
                self._walk_block(st.body, held, env)
                self._walk_block(st.orelse, held, env)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._collect(st.iter, held, env)
                self._collect(st.target, held, env)
                self._walk_block(st.body, held, env)
                self._walk_block(st.orelse, held, env)
                continue
            if isinstance(st, ast.While):
                self._collect(st.test, held, env)
                self._walk_block(st.body, held, env)
                self._walk_block(st.orelse, held, env)
                continue
            self._collect(st, held, env)

    def _acquire_release(self, env: "_WalkEnv",
                         st: ast.stmt) -> Optional[Tuple[str, bool]]:
        """``lk.acquire()`` / ``lk.release()`` statement -> (key,
        is_acquire)."""
        if not (isinstance(st, ast.Expr)
                and isinstance(st.value, ast.Call)
                and isinstance(st.value.func, ast.Attribute)
                and st.value.func.attr in ("acquire", "release")):
            return None
        lk = self._lock_key_of(env, st.value.func.value)
        if lk is None:
            return None
        return lk, st.value.func.attr == "acquire"

    def _releases_in(self, env: "_WalkEnv",
                     stmts: Sequence[ast.stmt]) -> List[str]:
        out = []
        for st in stmts:
            ar = self._acquire_release(env, st)
            if ar is not None and not ar[1]:
                out.append(ar[0])
        return out

    def _record_acquire(self, env: "_WalkEnv", lock: str,
                        held: Sequence[str], lineno: int):
        self.acquisitions.append(Acquisition(
            env.fn, lock, frozenset(held), lineno))

    def _collect(self, node: ast.AST, held: Sequence[str],
                 env: "_WalkEnv"):
        """Record accesses and call sites inside one statement/expr,
        skipping nested defs."""
        fheld = frozenset(held)
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))
            if isinstance(n, ast.Call):
                self._record_call(n, fheld, env)
                # receiver-mutating method == a write
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _MUTATORS:
                    key = self._access_key(env.fn, n.func.value, env)
                    if key is not None:
                        self._record_access(key, True, n.lineno,
                                            fheld, env)
                continue
            if isinstance(n, (ast.Attribute, ast.Name)):
                key = self._access_key(env.fn, n, env)
                if key is None:
                    continue
                write = isinstance(getattr(n, "ctx", None),
                                   (ast.Store, ast.Del))
                self._record_access(key, write, n.lineno, fheld, env)
                if not write and isinstance(n, ast.Attribute):
                    self._maybe_property_call(n, fheld, env)
                continue
            if isinstance(n, ast.Subscript):
                if isinstance(getattr(n, "ctx", None),
                              (ast.Store, ast.Del)):
                    key = self._access_key(env.fn, n.value, env)
                    if key is not None:
                        self._record_access(key, True, n.lineno,
                                            fheld, env)

    def _maybe_property_call(self, n: ast.Attribute,
                             fheld: FrozenSet[str], env: "_WalkEnv"):
        """An attribute read hitting an in-tree @property is a call
        edge into the getter."""
        for ck in self._self_types(env.fn, n.value):
            getter = self.properties.get((ck, n.attr))
            if getter:
                self.calls.append(CallOp(
                    env.fn, None, None, n.attr, 0, (getter,),
                    n.lineno, fheld))

    def _record_call(self, call: ast.Call, fheld: FrozenSet[str],
                     env: "_WalkEnv"):
        fn = env.fn
        mod = self.graph.modules.get(fn.modname)
        dotted = _dotted_from(call.func)
        gdot = self.graph.resolve_dotted(mod, dotted) \
            if (dotted and mod) else None
        attr = call.func.attr if isinstance(call.func, ast.Attribute) \
            else None
        targets = tuple(self.resolve_call(fn, call))
        self.calls.append(CallOp(fn, call, gdot, attr, len(call.args),
                                 targets, call.lineno, fheld))

    def _access_key(self, fn: FunctionInfo, expr: ast.AST,
                    env: Optional["_WalkEnv"] = None
                    ) -> Optional[Tuple[str, str]]:
        """(state key, display name) for a tracked location, else
        None."""
        if isinstance(expr, ast.Attribute):
            for ck in self._self_types(fn, expr.value):
                key = f"{ck}.{expr.attr}"
                disp = f"{ck.split(':', 1)[1]}.{expr.attr}"
                return key, disp
            return None
        if isinstance(expr, ast.Name):
            if env is None:
                return None
            name = expr.id
            if name in ("self", "cls") or name in env.locals:
                return None
            if name not in env.globals and \
                    name not in self._module_globals.get(
                        fn.modname, set()):
                return None
            mod = self.graph.modules.get(fn.modname)
            if mod is not None and name in mod.aliases:
                return None
            if _enclosing_locals(self, fn, name):
                return None
            key = f"{fn.modname}:{name}"
            return key, f"{fn.modname}.{name}"
        return None

    def _record_access(self, key: Tuple[str, str], write: bool,
                       lineno: int, fheld: FrozenSet[str],
                       env: "_WalkEnv"):
        k, disp = key
        if k in self.lock_kinds or k in self.safe_kinds:
            return     # locks and Event/Queue are shared by design
        own_class = f"{env.fn.modname}:{env.fn.class_name}" \
            if env.fn.class_name else None
        in_init = env.in_init and own_class is not None and \
            k.startswith(own_class + ".")
        self.accesses.append(Access(k, disp, env.fn, write, lineno,
                                    fheld, in_init))

    # -- thread roots & contexts ----------------------------------------
    def _find_thread_roots(self):
        for m in self.graph.modules.values():
            # handler subclasses: every method runs per-request
            for cqual in m.classes:
                if self._is_handler_class(m, cqual):
                    ck = f"{m.modname}:{cqual}"
                    for qual, info in m.functions.items():
                        if info.class_name == cqual:
                            self.thread_roots.setdefault(
                                info.qualname, f"http:{ck}")
            # Thread / Timer spawn sites
            for fn in m.functions.values():
                for st in _own_statements(fn.node):
                    for node in ast.walk(st):
                        if isinstance(node, ast.Call):
                            self._root_from_spawn(m, fn, node)

    def _is_handler_class(self, m: ModuleIndex, cqual: str) -> bool:
        seen: Set[Tuple[str, str]] = set()
        stack = [(m, cqual)]
        while stack:
            mm, cq = stack.pop()
            if (mm.modname, cq) in seen:
                continue
            seen.add((mm.modname, cq))
            for b in mm.classes.get(cq, []):
                gdot = self.graph.resolve_dotted(mm, b)
                if gdot in _HANDLER_BASES:
                    return True
                key = self.graph._class_key(mm, gdot)
                if key:
                    bmod, bqual = key.split(":", 1)
                    stack.append((self.graph.modules[bmod], bqual))
        return False

    def _root_from_spawn(self, m: ModuleIndex, fn: FunctionInfo,
                         call: ast.Call):
        dotted = _dotted_from(call.func)
        if dotted is None:
            return
        gdot = self.graph.resolve_dotted(m, dotted)
        if gdot not in _THREAD_FACTORIES:
            return
        target_expr = None
        if gdot == "threading.Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
            if target_expr is None and call.args:
                # Thread(group, target, ...) positional form
                if len(call.args) >= 2:
                    target_expr = call.args[1]
        else:   # Timer(interval, function)
            for kw in call.keywords:
                if kw.arg == "function":
                    target_expr = kw.value
            if target_expr is None and len(call.args) >= 2:
                target_expr = call.args[1]
        if target_expr is None:
            return
        if isinstance(target_expr, ast.Lambda):
            # calls inside the lambda body run on the new thread
            for n in ast.walk(target_expr.body):
                if isinstance(n, ast.Call):
                    for tq in self.resolve_call(fn, n):
                        self.thread_roots.setdefault(
                            tq, f"thread:{tq}")
            return
        for tq in sorted(self._fnrefs_of(fn, target_expr, m)):
            self.thread_roots.setdefault(tq, f"thread:{tq}")

    def _edges(self, qual: str) -> Set[str]:
        """Outgoing resolved call edges of a function (cached):
        resolved calls + property getters + constructor ``__init__`` +
        one level of nested defs."""
        cached = self._edges_cache.get(qual)
        if cached is not None:
            return cached
        out: Set[str] = set()
        fn = self.graph.functions.get(qual)
        if fn is not None:
            for op in self._calls_by_fn().get(qual, ()):
                out.update(op.targets)
            base = qual.split(":", 1)[1] + ".<locals>."
            m = self.graph.modules.get(fn.modname)
            if m is not None:
                for info in m.functions.values():
                    sub = info.qualname.split(":", 1)[1]
                    if sub.startswith(base):
                        out.add(info.qualname)
        self._edges_cache[qual] = out
        return out

    def _calls_by_fn(self) -> Dict[str, List[CallOp]]:
        if not hasattr(self, "_calls_index"):
            idx: Dict[str, List[CallOp]] = {}
            for op in self.calls:
                idx.setdefault(op.fn.qualname, []).append(op)
            self._calls_index = idx
        return self._calls_index

    def _propagate_contexts(self):
        # threaded contexts from the roots
        queue: List[Tuple[str, str]] = []

        def mark(qual: str, ctx: str):
            have = self.contexts.setdefault(qual, set())
            if ctx not in have:
                have.add(ctx)
                queue.append((qual, ctx))

        for qual, ctx in self.thread_roots.items():
            mark(qual, ctx)
        while queue:
            qual, ctx = queue.pop()
            for t in self._edges(qual):
                mark(t, ctx)

        # main context: seeded by functions nothing in-tree calls
        # (entry points) and module-level call targets
        callers: Dict[str, Set[str]] = {}
        for qual in self.graph.functions:
            for t in self._edges(qual):
                callers.setdefault(t, set()).add(qual)
        seeds: Set[str] = set()
        for qual in self.graph.functions:
            if qual in self.thread_roots:
                continue
            if not callers.get(qual):
                seeds.add(qual)
        for m in self.graph.modules.values():
            for st in m.tree.body:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                for n in ast.walk(st):
                    if isinstance(n, ast.Call):
                        dotted = _dotted_from(n.func)
                        if dotted is None:
                            continue
                        hit = self.graph._resolve_fn_arg(m, None,
                                                         n.func)
                        if hit is not None and \
                                hit.qualname not in self.thread_roots:
                            seeds.add(hit.qualname)
        for s in sorted(seeds):
            mark(s, "main")
        while queue:
            qual, ctx = queue.pop()
            for t in self._edges(qual):
                if t not in self.thread_roots:
                    mark(t, ctx)

    # -- caller lock inheritance ----------------------------------------
    def _inherit_caller_locks(self):
        """Meet-over-callers lock inheritance: a helper only ever
        called with lock L held is guarded by L. Thread roots and
        callback-invoked functions meet with the empty set (they can
        be entered bare)."""
        universe = frozenset(self.lock_kinds)
        sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for op in self.calls:
            for t in op.targets:
                sites.setdefault(t, []).append(
                    (op.fn.qualname, op.locks))
        callback_targets: Set[str] = set()
        for refs in self.fnrefs.values():
            callback_targets |= refs
        eff: Dict[str, FrozenSet[str]] = {}
        for qual in self.graph.functions:
            if qual in self.thread_roots or \
                    qual in callback_targets or qual not in sites:
                eff[qual] = frozenset()
            else:
                eff[qual] = universe
        for _ in range(30):
            changed = False
            for qual, slist in sites.items():
                if eff.get(qual) == frozenset() and (
                        qual in self.thread_roots
                        or qual in callback_targets):
                    continue
                if qual not in eff:
                    continue
                met: Optional[FrozenSet[str]] = None
                for caller, locks in slist:
                    here = locks | eff.get(caller, frozenset())
                    met = here if met is None else (met & here)
                if qual in self.thread_roots or \
                        qual in callback_targets:
                    met = frozenset()
                if met is not None and met != eff[qual]:
                    eff[qual] = met
                    changed = True
            if not changed:
                break
        self.inherited_locks = {q: l for q, l in eff.items() if l}
        # fold inherited locks into every recorded access / call /
        # acquisition of the affected functions
        for a in self.accesses:
            extra = eff.get(a.fn.qualname)
            if extra:
                a.locks = a.locks | extra
        for op in self.calls:
            extra = eff.get(op.fn.qualname)
            if extra:
                op.locks = op.locks | extra
        for acq in self.acquisitions:
            extra = eff.get(acq.fn.qualname)
            if extra:
                acq.held = acq.held | extra

    # -- derived views for the rules ------------------------------------
    def lock_pairs(self) -> Dict[Tuple[str, str],
                                 Tuple[str, int]]:
        """(outer, inner) lock-order pairs with one witness
        ``(function qualname, line)`` each."""
        pairs: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for acq in self.acquisitions:
            for outer in acq.held:
                pairs.setdefault((outer, acq.lock),
                                 (acq.fn.qualname, acq.lineno))
        return pairs


@dataclasses.dataclass
class _WalkEnv:
    """Per-function state threaded through the lock-scope walk."""

    fn: FunctionInfo
    globals: Set[str]
    locals: Set[str]
    in_init: bool


def _state_key(dest: Tuple) -> str:
    """Inference dest key -> flat state key string."""
    if dest[0] == "attr":
        return f"{dest[1]}.{dest[2]}"
    if dest[0] == "glob":
        return f"{dest[1]}:{dest[2]}"
    # local locks: scoped by the owning function
    return f"{dest[1]}.{dest[2]}"


def _module_assigned_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for st in tree.body:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(st.target, ast.Name):
                out.add(st.target.id)
    return out


def _global_decls(fn_node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for st in _own_statements(fn_node):
        for n in ast.walk(st):
            if isinstance(n, ast.Global):
                out.update(n.names)
    return out


def _local_names(fn_node: ast.AST, gl: Set[str]) -> Set[str]:
    """Names assigned in the function body (minus declared globals)."""
    out: Set[str] = set()
    for st in _own_statements(fn_node):
        for n in ast.walk(st):
            if isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Store):
                out.add(n.id)
            elif isinstance(n, (ast.For, ast.AsyncFor)) and \
                    isinstance(n.target, ast.Name):
                out.add(n.target.id)
            elif isinstance(n, ast.ExceptHandler) and n.name:
                out.add(n.name)
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if isinstance(item.optional_vars, ast.Name):
                        out.add(item.optional_vars.id)
            elif isinstance(n, (ast.ListComp, ast.SetComp,
                                ast.DictComp, ast.GeneratorExp)):
                for gen in n.generators:
                    for t in ast.walk(gen.target):
                        if isinstance(t, ast.Name):
                            out.add(t.id)
    return out - gl


def _own_statements(fn_node: ast.AST):
    """Statements lexically inside one function, nested defs
    skipped."""
    stack = list(getattr(fn_node, "body", []))
    while stack:
        st = stack.pop()
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        yield st
        for field in ("body", "orelse", "finalbody"):
            stack.extend(getattr(st, field, []))
        for h in getattr(st, "handlers", []):
            stack.extend(h.body)


def _enclosing_function_quals(qualname: str) -> List[str]:
    """Enclosing function qualnames of a nested def / nested-class
    method (``mod:A.__init__.<locals>._H.do_GET`` ->
    [``mod:A.__init__``])."""
    mod, _, qual = qualname.partition(":")
    out = []
    parts = qual.split(".<locals>.")
    for cut in range(len(parts) - 1, 0, -1):
        out.append(f"{mod}:{'.<locals>.'.join(parts[:cut])}")
    return out


def _enclosing_locals(tg: ThreadGraph, fn: FunctionInfo,
                      name: str) -> Set[str]:
    """Whether ``name`` is a local of an enclosing function (closure
    variable) — returns a set for truthiness at the call site."""
    for enc in _enclosing_function_quals(fn.qualname):
        einfo = tg.graph.functions.get(enc)
        if einfo is None:
            continue
        gl = _global_decls(einfo.node)
        if name in _local_names(einfo.node, gl) | set(einfo.params):
            return {name}
    return set()


def build(graph: CallGraph) -> ThreadGraph:
    """Build the thread graph over an existing jit call graph."""
    return ThreadGraph(graph)
