"""``pfxlint`` — a JAX-aware static-analysis suite for this repo.

Two rule families over the whole tree (``python -m codestyle.pfxlint``
from the repo root; full rule docs in ``docs/static_analysis.md``):

- **Traced-context hazards** (PFX101-PFX103): a module-level call
  graph (``callgraph.py``) marks every function reachable from a
  ``jax.jit`` / ``pjit`` / ``shard_map`` / ``pl.pallas_call``
  boundary, then host syncs, wall-clock/ambient-randomness reads and
  Python branches on tracer-typed values are flagged inside that set.
- **Contracts** (PFX201-PFX205 + D001-D006): dispatch counters vs the
  docs matrices (both directions), ``PFX_*`` knob documentation (both
  directions), Pallas call sites carrying an XLA fallback + counter,
  and the docstring checker's enforced tier, tree-wide.

Suppression: ``# pfxlint: disable=PFX101`` on the finding's line
(``disable-file=`` for a whole file); long-lived exemptions live in
``codestyle/pfxlint/baseline.txt`` with a justification comment.
Exit codes: 0 clean, 1 unbaselined findings, 2 usage/parse error.
"""

from .engine import (Finding, LintContext, LintResult, run_lint,   # noqa: F401
                     run_rules)

__all__ = ["Finding", "LintContext", "LintResult", "run_lint",
           "run_rules"]
