"""Module-level call graph with jit-reachability marking.

The traced-context hazard rules (PFX101-PFX103, ``docs/
static_analysis.md``) need to know which functions can execute under a
JAX trace. This module builds that set statically, in two passes over
the scanned tree's ASTs:

1. **Index** every module: its import aliases (``import jax.numpy as
   jnp``, ``from ..observability import metrics``, relative levels
   resolved against the module's package), every function/method
   definition (nested functions get ``outer.<locals>.inner``
   qualnames), every class with its base list, and every call site
   inside each function with enough syntax kept around to resolve it
   later (dotted path, ``self.`` receiver, bare name).

2. **Resolve and propagate**: call targets are resolved through the
   alias table to either an external dotted name (``jax.jit``) or an
   in-tree function. Functions become *roots* when they are

   - decorated with / passed to a tracing wrapper — ``jax.jit``,
     ``pjit``, ``shard_map``, ``pl.pallas_call`` (the boundary set the
     repo admits SPMD programs through) plus the propagating tracers
     ``vmap`` / ``grad`` / ``value_and_grad`` / ``checkpoint`` /
     ``remat`` / ``lax.{scan,while_loop,fori_loop,cond,switch,map,
     associative_scan}`` — including through ``functools.partial``
     (whose bound argument names are recorded as STATIC params);
   - the ``__call__`` / ``setup`` / ``@nn.compact`` methods of a
     ``flax.linen.Module`` subclass (flax modules in this repo only
     ever run under ``Module.apply`` inside a jitted step);
   - arguments of a ``*.defvjp(fwd, bwd)`` call (custom-VJP halves
     run under the autodiff trace).

   Reachability then spreads breadth-first along resolved call edges
   (bare names in scope, ``self.method`` through in-tree MRO, imported
   names, ``module.attr``), and into functions *defined inside* a
   reachable function (conservative: a nested def is usually a scan
   body or branch closure handed to an unresolvable higher-order
   callee).

For functions rooted DIRECTLY in a tracing wrapper the parameter list
is trustworthy: every param not claimed by ``static_argnames`` /
``static_argnums`` / a ``partial`` binding IS a tracer at run time, so
rules may treat bare comparisons on those names as sound findings, not
heuristics (``FunctionInfo.tracer_params``). For functions reached
only transitively, only parameters with array-ish annotations
(``jax.Array``, ``jnp.ndarray``, ...) are nominated — unannotated
params of helpers are very often static config threaded through, and a
lint that cries wolf gets disabled.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

#: wrappers whose function-valued arguments execute under a trace.
#: Keys are fully-qualified names after alias resolution; ``jit`` and
#: ``pjit`` additionally carry static-arg semantics.
TRACING_WRAPPERS = {
    "jax.jit", "jax.pjit",
    "jax.experimental.pjit.pjit",
    "jax.experimental.shard_map.shard_map",
    "jax.sharding.shard_map",
    "jax.shard_map",
    "jax.experimental.pallas.pallas_call",
    "jax.vmap", "jax.pmap",
    "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.ad_checkpoint.checkpoint",
    "jax.custom_vjp", "jax.custom_jvp",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan",
    "flax.linen.scan", "flax.linen.remat", "flax.linen.jit",
}

#: wrappers with jit-style ``static_argnames`` / ``static_argnums``
_JIT_LIKE = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}

#: annotations that nominate a parameter as array/tracer-typed
_ARRAY_ANNOTATIONS = {
    "jax.Array", "jax.numpy.ndarray", "jnp.ndarray", "np.ndarray",
    "numpy.ndarray", "Array", "ArrayLike", "jax.typing.ArrayLike",
    "chex.Array",
}

_FLAX_MODULE = {"flax.linen.Module", "flax.linen.nn.Module"}


@dataclasses.dataclass
class CallRef:
    """One call site inside a function, pre-resolution."""

    node: ast.Call
    dotted: Optional[str]       # "a.b.c" when func is a Name/Attribute chain
    is_self: bool               # receiver is ``self`` / ``cls``


@dataclasses.dataclass
class FunctionInfo:
    """One function/method definition and everything rules ask of it."""

    qualname: str               # "pkg.mod:Class.method" / "pkg.mod:f"
    modname: str
    path: str
    node: ast.AST               # FunctionDef / AsyncFunctionDef / Lambda
    class_name: Optional[str]   # enclosing class qualpart, if a method
    params: List[str] = dataclasses.field(default_factory=list)
    annotations: Dict[str, Optional[ast.AST]] = \
        dataclasses.field(default_factory=dict)
    calls: List[CallRef] = dataclasses.field(default_factory=list)
    static_params: Set[str] = dataclasses.field(default_factory=set)
    direct_traced: bool = False     # rooted straight in a wrapper
    traced_via: Optional[str] = None    # human-readable root reason
    jit_reachable: bool = False
    parent: Optional[str] = None    # enclosing function qualname

    @property
    def tracer_params(self) -> Set[str]:
        """Parameter names rules may treat as tracer-typed.

        Sound for direct roots (non-static params of a jitted
        function ARE tracers); annotation-gated for transitive
        reachability (see module docstring).
        """
        skip = {"self", "cls"} | self.static_params
        if self.direct_traced:
            return {p for p in self.params if p not in skip}
        out = set()
        for p in self.params:
            if p in skip:
                continue
            ann = self.annotations.get(p)
            if ann is not None and _mentions_array(ann):
                out.add(p)
        return out


def _mentions_array(ann: ast.AST) -> bool:
    """Whether an annotation AST mentions an array-ish type (walks
    through ``Optional[...]`` / unions / string annotations)."""
    for node in ast.walk(ann):
        name = _dotted_from(node)
        if name and (name in _ARRAY_ANNOTATIONS
                     or name.split(".")[-1] in ("Array", "ndarray")):
            return True
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if any(tok in node.value for tok in ("Array", "ndarray")):
                return True
    return False


def _dotted_from(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ModuleIndex(ast.NodeVisitor):
    """Pass 1: per-module symbol/import/function/class tables."""

    def __init__(self, modname: str, path: str, tree: ast.Module):
        self.modname = modname
        self.path = path
        self.tree = tree
        self.aliases: Dict[str, str] = {}   # local name -> dotted target
        self.functions: Dict[str, FunctionInfo] = {}   # qual -> info
        self.classes: Dict[str, List[str]] = {}   # class qual -> base dots
        self._scope: List[str] = []
        self._class: List[str] = []
        self.visit(tree)

    # -- imports -------------------------------------------------------
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0])

    def visit_ImportFrom(self, node: ast.ImportFrom):
        """Record aliases, resolving relative imports against
        ``self.modname`` so ``from ..observability import metrics``
        lands on its absolute dotted target."""
        if node.level:
            pkg = self.modname.split(".")
            # ``from . import x`` inside pkg.mod: level 1 strips the
            # module leaf; each extra level strips one package
            pkg = pkg[: len(pkg) - node.level]
            base = ".".join(pkg + ([node.module] if node.module else []))
        else:
            base = node.module or ""
        for a in node.names:
            if a.name == "*":
                continue
            target = f"{base}.{a.name}" if base else a.name
            self.aliases[a.asname or a.name] = target

    # -- defs ----------------------------------------------------------
    def _qual(self, name: str) -> str:
        return ".".join(self._scope + [name]) if self._scope else name

    def visit_ClassDef(self, node: ast.ClassDef):
        qual = self._qual(node.name)
        self.classes[qual] = [d for d in
                              (_dotted_from(b) for b in node.bases) if d]
        self._scope.append(node.name)
        self._class.append(qual)
        for stmt in node.body:
            self.visit(stmt)
        self._class.pop()
        self._scope.pop()

    def _visit_fn(self, node):
        qual = self._qual(node.name)
        info = FunctionInfo(
            qualname=f"{self.modname}:{qual}",
            modname=self.modname, path=self.path, node=node,
            class_name=self._class[-1] if self._class else None,
            parent=(f"{self.modname}:{'.'.join(self._scope)}"
                    if self._scope and not self._class else None))
        a = node.args
        for arg in (list(a.posonlyargs) + list(a.args)
                    + list(a.kwonlyargs)):
            info.params.append(arg.arg)
            info.annotations[arg.arg] = arg.annotation
        self.functions[qual] = info
        self._scope.append(node.name + ".<locals>")
        # collect calls lexically inside THIS function, not nested defs
        for stmt in node.body:
            self._collect_calls(stmt, info)
        for stmt in node.body:
            self.visit(stmt)
        self._scope.pop()

    def visit_FunctionDef(self, node):        # noqa: D102 (visitor)
        self._visit_fn(node)

    def visit_AsyncFunctionDef(self, node):   # noqa: D102 (visitor)
        self._visit_fn(node)

    def _collect_calls(self, stmt: ast.AST, info: FunctionInfo):
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node is not stmt:
                continue   # nested defs walked separately (note: walk
                # still descends — filtered at use via lineno ownership;
                # call OWNERSHIP only matters for edges, which are
                # conservative, so double-attribution is harmless)
            if isinstance(node, ast.Call):
                dotted = _dotted_from(node.func)
                is_self = bool(dotted) and \
                    dotted.split(".")[0] in ("self", "cls")
                info.calls.append(CallRef(node, dotted, is_self))


class CallGraph:
    """The resolved, reachability-marked graph over scanned modules."""

    def __init__(self, modules: Dict[str, ModuleIndex]):
        self.modules = modules
        #: qualname ("mod:qual") -> FunctionInfo
        self.functions: Dict[str, FunctionInfo] = {}
        for m in modules.values():
            for qual, info in m.functions.items():
                self.functions[info.qualname] = info
        self._flax_classes = self._find_flax_classes()
        self._mark_roots()
        self._propagate()

    # -- resolution helpers -------------------------------------------
    def resolve_dotted(self, mod: ModuleIndex, dotted: str) -> str:
        """Resolve a local dotted name to a global one via the module's
        alias table (``fa.flash_decode`` ->
        ``paddlefleetx_tpu.ops.pallas.flash_attention.flash_decode``).
        """
        head, _, rest = dotted.partition(".")
        target = mod.aliases.get(head)
        if target is None:
            return dotted
        return f"{target}.{rest}" if rest else target

    def _function_for_global(self, gdot: str) -> Optional[FunctionInfo]:
        """Global dotted name -> in-tree FunctionInfo, if any."""
        # exact module:attr split, longest module prefix first
        parts = gdot.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:cut])
            m = self.modules.get(modname)
            if m is not None:
                qual = ".".join(parts[cut:])
                info = m.functions.get(qual)
                if info is not None:
                    return info
                # classname -> its __call__ won't be a call target here
                return None
        return None

    def _find_flax_classes(self) -> Set[str]:
        """Fixpoint of in-tree ``flax.linen.Module`` subclasses, as
        ``modname:ClassQual`` keys."""
        flax: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for m in self.modules.values():
                for cqual, bases in m.classes.items():
                    key = f"{m.modname}:{cqual}"
                    if key in flax:
                        continue
                    for b in bases:
                        gdot = self.resolve_dotted(m, b)
                        if gdot in _FLAX_MODULE or \
                                self._class_key(m, gdot) in flax:
                            flax.add(key)
                            changed = True
                            break
        return flax

    def _class_key(self, mod: ModuleIndex, gdot: str) -> Optional[str]:
        """Global dotted name -> in-tree ``modname:ClassQual`` key."""
        parts = gdot.split(".")
        for cut in range(len(parts), 0, -1):
            modname = ".".join(parts[:cut])
            m = self.modules.get(modname)
            if m is not None:
                qual = ".".join(parts[cut:])
                if qual in m.classes:
                    return f"{modname}:{qual}"
                return None
        # bare name in the same module
        if gdot in mod.classes:
            return f"{mod.modname}:{gdot}"
        return None

    # -- root marking --------------------------------------------------
    def _mark_root(self, info: FunctionInfo, reason: str,
                   static: Set[str] = frozenset()):
        info.direct_traced = True
        info.static_params |= set(static)
        if not info.traced_via:
            info.traced_via = reason

    def _unwrap_partial(self, mod: ModuleIndex, node: ast.AST
                        ) -> Tuple[Optional[ast.AST], Set[str]]:
        """``partial(f, a, k=v)`` -> (f-node, static names bound)."""
        if not isinstance(node, ast.Call):
            return node, set()
        dotted = _dotted_from(node.func)
        if dotted is None:
            return node, set()
        gdot = self.resolve_dotted(mod, dotted)
        if gdot not in ("functools.partial", "partial"):
            return node, set()
        if not node.args:
            return None, set()
        inner = node.args[0]
        static = {kw.arg for kw in node.keywords if kw.arg}
        # positional partial bindings claim leading params — resolved
        # by the caller once the target's param list is known
        n_pos = len(node.args) - 1
        static.add(f"<pos:{n_pos}>")
        return inner, static

    def _static_from_jit_kwargs(self, call: ast.Call,
                                target: FunctionInfo) -> Set[str]:
        """``static_argnames`` / ``static_argnums`` keyword payloads."""
        static: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, str):
                        static.add(c.value)
            elif kw.arg == "static_argnums":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, int):
                        params = [p for p in target.params
                                  if p not in ("self", "cls")]
                        if 0 <= c.value < len(params):
                            static.add(params[c.value])
        return static

    def _resolve_fn_arg(self, mod: ModuleIndex,
                        owner: Optional[FunctionInfo],
                        node: ast.AST) -> Optional[FunctionInfo]:
        """An argument expression -> the FunctionInfo it names."""
        dotted = _dotted_from(node)
        if dotted is None:
            return None
        head = dotted.split(".")[0]
        if head in ("self", "cls") and owner and owner.class_name:
            meth = dotted.split(".", 1)[1] if "." in dotted else None
            if meth:
                return self._method_on(mod, owner.class_name, meth)
            return None
        # bare name: sibling nested function of the owner first
        if "." not in dotted and owner is not None:
            base = owner.qualname.split(":", 1)[1]
            sib = f"{base}.<locals>.{dotted}"
            hit = mod.functions.get(sib)
            if hit is not None:
                return hit
        gdot = self.resolve_dotted(mod, dotted)
        hit = self._function_for_global(gdot)
        if hit is not None:
            return hit
        # bare (or Class.method) name defined in this same module
        return mod.functions.get(dotted)

    def _method_on(self, mod: ModuleIndex, class_qual: str,
                   meth: str) -> Optional[FunctionInfo]:
        """Look up a method through the in-tree single-module MRO."""
        seen = set()
        stack = [(mod, class_qual)]
        while stack:
            m, cq = stack.pop()
            if (m.modname, cq) in seen:
                continue
            seen.add((m.modname, cq))
            info = m.functions.get(f"{cq}.{meth}")
            if info is not None:
                return info
            for b in m.classes.get(cq, []):
                key = self._class_key(m, self.resolve_dotted(m, b))
                if key:
                    bmod, bqual = key.split(":", 1)
                    stack.append((self.modules[bmod], bqual))
        return None

    def _apply_partial_positional(self, info: FunctionInfo,
                                  static: Set[str]):
        """Translate ``<pos:N>`` partial markers into leading param
        names."""
        markers = {s for s in static if s.startswith("<pos:")}
        names = static - markers
        n = sum(int(s[5:-1]) for s in markers)
        params = [p for p in info.params if p not in ("self", "cls")]
        names |= set(params[:n])
        return names

    def _mark_roots(self):
        for mod in self.modules.values():
            # decorators
            for qual, info in mod.functions.items():
                for deco in getattr(info.node, "decorator_list", []):
                    target, static = self._unwrap_partial(mod, deco)
                    if target is None:
                        continue
                    dotted = _dotted_from(
                        target.func if isinstance(target, ast.Call)
                        else target)
                    if dotted is None:
                        continue
                    gdot = self.resolve_dotted(mod, dotted)
                    if gdot in TRACING_WRAPPERS:
                        if isinstance(target, ast.Call) and \
                                gdot in _JIT_LIKE:
                            static |= self._static_from_jit_kwargs(
                                target, info)
                        if isinstance(deco, ast.Call) and \
                                gdot in _JIT_LIKE:
                            static |= self._static_from_jit_kwargs(
                                deco, info)
                        static = self._apply_partial_positional(
                            info, static)
                        self._mark_root(
                            info, f"decorated @{gdot}", static)
                    elif gdot in ("flax.linen.compact", "nn.compact"):
                        self._mark_root(info, "flax @nn.compact")
                # flax module methods
                if info.class_name and \
                        f"{mod.modname}:{info.class_name}" in \
                        self._flax_classes and \
                        info.node.name in ("__call__", "setup"):
                    self._mark_root(
                        info,
                        f"flax Module method {info.class_name}."
                        f"{info.node.name}")
            # call-site wrapping: jax.jit(fn, ...), shard_map(fn, ...),
            # pl.pallas_call(kernel, ...), lax.scan(body, ...),
            # f.defvjp(fwd, bwd) — anywhere in the module
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted_from(node.func)
                if dotted is None:
                    continue
                if dotted.endswith(".defvjp") or \
                        dotted.endswith(".defjvp"):
                    owner = self._owner_of(mod, node)
                    for arg in node.args:
                        hit = self._resolve_fn_arg(mod, owner, arg)
                        if hit is not None:
                            self._mark_root(hit, "custom-VJP half")
                    continue
                gdot = self.resolve_dotted(mod, dotted)
                if gdot not in TRACING_WRAPPERS:
                    continue
                owner = self._owner_of(mod, node)
                for arg in node.args:
                    target, static = self._unwrap_partial(mod, arg)
                    if target is None:
                        continue
                    hit = self._resolve_fn_arg(mod, owner, target)
                    if hit is None:
                        continue
                    if gdot in _JIT_LIKE:
                        static |= self._static_from_jit_kwargs(node, hit)
                    static = self._apply_partial_positional(hit, static)
                    self._mark_root(
                        hit, f"passed to {gdot}", static)

    def _owner_of(self, mod: ModuleIndex,
                  call: ast.Call) -> Optional[FunctionInfo]:
        """The innermost function whose span contains the call."""
        best = None
        for info in mod.functions.values():
            node = info.node
            if node.lineno <= call.lineno <= \
                    (node.end_lineno or node.lineno):
                if best is None or node.lineno > best.node.lineno:
                    best = info
        return best

    # -- propagation ---------------------------------------------------
    def _propagate(self):
        queue = [f for f in self.functions.values() if f.direct_traced]
        for f in queue:
            f.jit_reachable = True
        while queue:
            fn = queue.pop()
            mod = self.modules[fn.modname]
            targets: List[FunctionInfo] = []
            for ref in fn.calls:
                if ref.dotted is None:
                    continue
                hit = self._resolve_fn_arg(mod, fn, ref.dotted and
                                           ref.node.func)
                if hit is not None:
                    targets.append(hit)
            # nested defs of a traced function are conservatively
            # traced too (scan bodies, cond branches)
            base = fn.qualname.split(":", 1)[1] + ".<locals>."
            for qual, info in mod.functions.items():
                if info.qualname.split(":", 1)[1].startswith(base) and \
                        "." not in info.qualname.split(":", 1)[1][
                            len(base):]:
                    targets.append(info)
            for t in targets:
                if not t.jit_reachable:
                    t.jit_reachable = True
                    if not t.traced_via:
                        t.traced_via = f"called from {fn.qualname}"
                    queue.append(t)

    # -- public lookups ------------------------------------------------
    def reachable_functions(self) -> List[FunctionInfo]:
        return [f for f in self.functions.values() if f.jit_reachable]

    def module(self, modname: str) -> Optional[ModuleIndex]:
        return self.modules.get(modname)


def modname_for(relpath: str) -> str:
    """Repo-relative path -> dotted module name (``bench.py`` ->
    ``bench``; package ``__init__.py`` -> the package)."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [seg for seg in p.replace("\\", "/").split("/") if seg]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def build(files: Dict[str, ast.Module]) -> CallGraph:
    """Build the graph from ``{relpath: parsed AST}``."""
    modules = {}
    for relpath, tree in files.items():
        modname = modname_for(relpath)
        modules[modname] = ModuleIndex(modname, relpath, tree)
    return CallGraph(modules)
