"""The pfxlint engine: file collection, rule orchestration,
suppression, baseline.

The engine owns everything that is not a rule: walking the tree,
parsing sources once, building the call graph (``callgraph.py``),
handing a :class:`LintContext` to each rule module, then filtering the
raw findings through inline suppressions (``# pfxlint:
disable=RULE``) and the checked-in baseline
(``codestyle/pfxlint/baseline.txt``).

Baselines are fingerprint-based, NOT line-based: a fingerprint is
``path::CODE::key`` where ``key`` is a rule-chosen stable detail (a
counter name, a function qualname + hazard token, a docstring
message), so unrelated edits moving a finding by ten lines do not
churn the file. ``--write-baseline`` regenerates it; comment lines
are preserved conventionally by writing justifications above blocks
(regeneration keeps findings sorted so diffs stay reviewable).

Everything here is stdlib-only on purpose — the CI gate and the
pre-commit hook must run before (and without) the jax toolchain
installing.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import callgraph
from . import threadgraph

#: directories never scanned, wherever they appear
EXCLUDE_DIRS = {
    ".git", "__pycache__", ".github", ".claude", ".pytest_cache",
    "tests",            # the tier-1 suite lints itself via pytest
    "output", "bench_log", "profiler_log", "node_modules",
}

#: docs scanned by the contract rules
DOCS_GLOB_DIR = "docs"

_SUPPRESS_RE = re.compile(
    r"#\s*pfxlint:\s*disable(?P<scope>-file)?="
    r"(?P<codes>[A-Za-z0-9_,\s]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation, with a line-independent fingerprint."""

    path: str
    line: int
    code: str
    message: str
    key: str = ""          # stable detail; message used when empty

    def fingerprint(self) -> str:
        return f"{self.path}::{self.code}::{self.key or self.message}"

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"


@dataclasses.dataclass
class SourceFile:
    """One parsed Python file."""

    path: str              # repo-relative, forward slashes
    text: str
    tree: ast.Module
    lines: List[str]
    #: line -> codes disabled on that line ("*" disables all)
    suppressions: Dict[int, Set[str]] = \
        dataclasses.field(default_factory=dict)
    #: codes disabled for the whole file
    file_suppressions: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class DocFile:
    """One documentation file the contract rules read."""

    path: str
    text: str
    lines: List[str]


class LintContext:
    """Everything a rule may look at; built once per run."""

    def __init__(self, py_files: List[SourceFile],
                 docs: List[DocFile], root: str):
        self.py_files = py_files
        self.docs = docs
        self.root = root
        self.callgraph = callgraph.build(
            {f.path: f.tree for f in py_files})
        self.threadgraph = threadgraph.build(self.callgraph)

    def file(self, path: str) -> Optional[SourceFile]:
        for f in self.py_files:
            if f.path == path:
                return f
        return None

    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     docs: Optional[Dict[str, str]] = None,
                     root: str = "<memory>") -> "LintContext":
        """Build a context from in-memory sources (the test path).

        Args:
            sources (dict): repo-relative path -> Python source text.
            docs (dict): repo-relative path -> markdown text.
            root (str): reported root, cosmetic only.

        Returns:
            LintContext over exactly the given files.

        Raises:
            SyntaxError: when a source does not parse.
        """
        py = [_parse_source(p, t) for p, t in sorted(sources.items())]
        dd = [DocFile(p, t, t.splitlines())
              for p, t in sorted((docs or {}).items())]
        return cls(py, dd, root)


def _parse_source(path: str, text: str) -> SourceFile:
    tree = ast.parse(text, filename=path)
    sf = SourceFile(path, text, tree, text.splitlines())
    for i, line in enumerate(sf.lines, 1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        codes = {c.strip() for c in m.group("codes").split(",")
                 if c.strip()}
        if m.group("scope"):
            sf.file_suppressions |= codes
        else:
            sf.suppressions.setdefault(i, set()).update(codes)
    return sf


def collect_files(root: str, paths: Optional[Sequence[str]] = None
                  ) -> Tuple[List[SourceFile], List[DocFile]]:
    """Walk the tree (or explicit paths) into parsed sources + docs.

    Args:
        root (str): repository root all paths are made relative to.
        paths (list): optional explicit files/dirs; default full tree.

    Returns:
        ``(py_files, docs)`` with stable, sorted ordering.

    Raises:
        SyntaxError: when a Python source fails to parse — a broken
            file must fail the gate loudly, not fall out of coverage.
    """
    root = os.path.abspath(root)
    py: List[SourceFile] = []
    seen: Set[str] = set()

    def add_py(abspath: str):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        if rel in seen:
            return
        seen.add(rel)
        with open(abspath, "r", encoding="utf-8") as f:
            py.append(_parse_source(rel, f.read()))

    targets = [os.path.join(root, p) for p in paths] if paths \
        else [root]
    for target in targets:
        if os.path.isfile(target):
            if target.endswith(".py"):
                add_py(target)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    add_py(os.path.join(dirpath, name))

    docs: List[DocFile] = []
    docs_dir = os.path.join(root, DOCS_GLOB_DIR)
    if os.path.isdir(docs_dir):
        for name in sorted(os.listdir(docs_dir)):
            if name.endswith(".md"):
                p = os.path.join(docs_dir, name)
                with open(p, "r", encoding="utf-8") as f:
                    text = f.read()
                docs.append(DocFile(f"docs/{name}", text,
                                    text.splitlines()))
    return py, docs


# -- baseline ----------------------------------------------------------

def load_baseline(path: str) -> List[str]:
    """Baseline fingerprints, in file order (comments/blanks skipped)."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.append(line)
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   header: str = "") -> None:
    """Serialize findings as a fresh baseline file.

    Args:
        path (str): destination file.
        findings (list): findings to carry; sorted for diff stability.
        header (str): optional comment block for the top of the file.
    """
    lines = [
        "# pfxlint baseline — findings carried, not fixed.",
        "# One fingerprint per line: path::CODE::key. Lines starting",
        "# with '#' are justification comments. Regenerate with:",
        "#   python -m codestyle.pfxlint --write-baseline",
    ]
    if header:
        lines += ["#", *("# " + h for h in header.splitlines())]
    lines += sorted({f.fingerprint() for f in findings})
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")


# -- orchestration -----------------------------------------------------

@dataclasses.dataclass
class LintResult:
    """Outcome of one lint run, pre-split for reporting."""

    findings: List[Finding]            # actionable (rc 1 when any)
    suppressed: List[Finding]          # killed by inline comments
    baselined: List[Finding]           # carried by the baseline file
    unused_baseline: List[str]         # stale fingerprints

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def suppression_counts(self) -> Dict[str, int]:
        """Inline suppressions per rule code — the creep metric
        ``--stats`` prints so a quietly growing pile of disables is
        visible in CI logs."""
        out: Dict[str, int] = {}
        for f in self.suppressed:
            out[f.code] = out.get(f.code, 0) + 1
        return out


def _all_rules():
    from .rules import ALL_RULES
    return ALL_RULES


def run_rules(ctx: LintContext,
              select: Optional[Set[str]] = None,
              ignore: Optional[Set[str]] = None) -> List[Finding]:
    """Raw findings from every (selected) rule module, sorted."""
    findings: List[Finding] = []
    for rule in _all_rules():
        if select and not (set(rule.CODES) & select):
            continue
        findings.extend(rule.check(ctx))
    if select:
        findings = [f for f in findings if f.code in select]
    if ignore:
        findings = [f for f in findings if f.code not in ignore]
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.message))
    return findings


def apply_suppressions(ctx: LintContext, findings: Sequence[Finding]
                       ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed) via inline comments."""
    kept, suppressed = [], []
    by_path = {f.path: f for f in ctx.py_files}
    for f in findings:
        sf = by_path.get(f.path)
        codes = set()
        if sf is not None:
            codes |= sf.file_suppressions
            codes |= sf.suppressions.get(f.line, set())
        if f.code in codes or "all" in codes:
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


def run_lint(root: str,
             paths: Optional[Sequence[str]] = None,
             select: Optional[Set[str]] = None,
             ignore: Optional[Set[str]] = None,
             baseline_path: Optional[str] = None,
             use_baseline: bool = True) -> LintResult:
    """Full pipeline over a directory tree.

    Args:
        root (str): repository root.
        paths (list): optional explicit sub-paths (full tree default).
        select (set): restrict to these rule codes.
        ignore (set): drop these rule codes.
        baseline_path (str): baseline file; default
            ``codestyle/pfxlint/baseline.txt`` under ``root``.
        use_baseline (bool): set False to see every finding.

    Returns:
        LintResult with actionable / suppressed / baselined splits.
    """
    py, docs = collect_files(root, paths)
    ctx = LintContext(py, docs, root)
    raw = run_rules(ctx, select=select, ignore=ignore)
    kept, suppressed = apply_suppressions(ctx, raw)
    baselined: List[Finding] = []
    unused: List[str] = []
    if use_baseline:
        if baseline_path is None:
            baseline_path = os.path.join(
                root, "codestyle", "pfxlint", "baseline.txt")
        entries = set(load_baseline(baseline_path))
        hit: Set[str] = set()
        still: List[Finding] = []
        for f in kept:
            fp = f.fingerprint()
            if fp in entries:
                baselined.append(f)
                hit.add(fp)
            else:
                still.append(f)
        kept = still
        unused = sorted(entries - hit)
    return LintResult(kept, suppressed, baselined, unused)
