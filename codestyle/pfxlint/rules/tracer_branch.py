"""PFX103 — Python control flow branching on a tracer-typed value.

``if x > 0:`` on a tracer raises ``TracerBoolConversionError`` at
trace time — IF the branch is ever traced. The ones that hide are in
rarely-exercised config corners, then detonate in production the
first time a new shape routes through them. The call graph makes this
checkable statically: for a function rooted DIRECTLY in ``jax.jit``
(or another tracing wrapper), every parameter not claimed by
``static_argnames`` / ``static_argnums`` / a ``partial`` binding IS a
tracer, so a bare comparison on it in an ``if`` / ``while`` /
``assert`` test is a real bug, not a style nit. For transitively
reachable helpers only array-annotated parameters are held to this
(unannotated helper params are usually static config threaded
through — flagging those would bury the signal).

Exemptions: ``x is None`` / ``x is not None`` guards, ``isinstance``
checks, and any use through an attribute (``x.shape[0] > 1`` is
static; ``x.sum() > 0`` sneaks past — a documented blind spot, the
dynamic error still catches it).
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..engine import Finding
from . import own_nodes

CODES = ("PFX103",)


def _excluded_names(test: ast.AST) -> Set[int]:
    """ids of Name nodes used via attributes / len / isinstance /
    getattr — never treated as direct tracer reads."""
    out: Set[int] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    out.add(id(sub))
        elif isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) \
                else None
            if fname in ("len", "isinstance", "getattr", "hasattr",
                         "callable"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        out.add(id(sub))
    return out


def _compare_hits(test: ast.AST, tracers: Set[str]) -> List[str]:
    """Tracer params compared (non-``is None``) inside a test expr."""
    excluded = _excluded_names(test)
    hits: List[str] = []
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare):
            continue
        if all(isinstance(op, (ast.Is, ast.IsNot))
               for op in node.ops):
            continue
        for operand in [node.left] + list(node.comparators):
            for sub in ast.walk(operand):
                if isinstance(sub, ast.Name) and \
                        sub.id in tracers and id(sub) not in excluded:
                    hits.append(sub.id)
    return hits


def check(ctx) -> List[Finding]:
    """Scan reachable functions for Python branches on tracer params."""
    findings: List[Finding] = []
    for fn in ctx.callgraph.reachable_functions():
        tracers = fn.tracer_params
        if not tracers:
            continue
        for node in own_nodes(fn.node):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            else:
                continue
            for name in sorted(set(_compare_hits(test, tracers))):
                kind = type(node).__name__.lower()
                findings.append(Finding(
                    fn.path, node.lineno, "PFX103",
                    f"Python `{kind}` compares tracer-typed param "
                    f"`{name}` in jit-reachable "
                    f"`{fn.qualname.split(':', 1)[1]}` — use "
                    f"`jnp.where`/`lax.cond`, or mark the argument "
                    f"static (traced via: {fn.traced_via})",
                    key=f"{fn.qualname}:{kind}:{name}"))
    return findings
