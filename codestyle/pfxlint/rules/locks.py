"""PFX302 / PFX303 — lock-order inversion and blocking under a lock.

PFX302 (static deadlock smell): somewhere lock A is held while lock B
is acquired, and somewhere else B is held while A is acquired. With
two threads running those paths concurrently each can hold one lock
and wait forever on the other. Acquisition pairs come from the
thread graph's lock-scope walk, with caller-held locks inherited
(``helper()`` called under A that takes B contributes the (A, B)
pair). Re-acquiring a non-reentrant ``threading.Lock`` that is
already held — directly or through a helper only ever called with it
held — self-deadlocks and is reported on the same code.

PFX303 (blocking call while holding a lock): a lock region should be
a few loads and stores, never I/O or an unbounded wait. Flagged while
any lock is held:

- resolved blocking callables — ``time.sleep``, ``jax.device_get``,
  ``jax.block_until_ready``, ``select.select``, ``subprocess.*``,
  ``socket.create_connection``;
- blocking METHODS by name, gated on the argument shape that
  distinguishes them from innocent namesakes: ``.get()`` / ``.join()``
  / ``.result()`` / ``.shutdown()`` with zero positional args (a
  ``dict.get(key)`` or ``",".join(xs)`` never blocks), ``.wait(...)``
  / ``.put(...)`` / ``.recv(...)`` / ``.accept()`` / ``.connect(...)``
  / ``.sendall(...)`` / ``.serve_forever()`` / ``.block_until_ready()``
  with any arity;
- one call level deep: a call made under a lock into an in-tree
  function that itself contains a direct blocking call.

``Condition.wait`` on the condition's OWN lock is the correct wait
idiom (it releases while waiting) and is exempt. ``flush``/``fsync``
are deliberately NOT in the set: a durable-log writer that fsyncs
under its lock is a design choice, not a deadlock.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding

CODES = ("PFX302", "PFX303")

_BLOCKING_GDOTS = {
    "time.sleep", "jax.device_get", "jax.block_until_ready",
    "select.select", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}

#: method name -> required positional-arg predicate (None = any)
_BLOCKING_METHODS = {
    "get": 0, "join": 0, "result": 0, "shutdown": 0,
    "accept": 0, "serve_forever": 0, "join_thread": 0,
    "wait": None, "wait_for": None, "put": None, "recv": None,
    "recv_into": None, "connect": None, "sendall": None,
    "block_until_ready": None, "wait_until_finished": None,
}


def _short(lock: str) -> str:
    return lock.split(":", 1)[-1]


def _blocking_what(op) -> str:
    """Why a call op is considered blocking, or '' when it is not."""
    if op.node is None:
        return ""
    if op.gdot in _BLOCKING_GDOTS:
        return op.gdot
    if op.attr in _BLOCKING_METHODS:
        arity = _BLOCKING_METHODS[op.attr]
        if arity is None or op.n_pos == arity:
            return f".{op.attr}()"
    return ""


def _is_condition_wait(tg, op) -> bool:
    """``cond.wait()`` where ``cond`` is a registered Condition —
    the one blocking-under-lock shape that is the POINT of the
    lock."""
    if op.attr not in ("wait", "wait_for") or op.node is None:
        return False
    recv = op.node.func.value if isinstance(op.node.func,
                                            ast.Attribute) else None
    if recv is None:
        return False
    key = tg._access_key(op.fn, recv)
    if key is not None:
        return tg.lock_kinds.get(key[0]) == "Condition"
    # module-global / function-local conditions: _access_key needs a
    # walk env for bare names, so resolve against the lock table the
    # same way the lock-scope walker does
    if isinstance(recv, ast.Name):
        for cand in (f"{op.fn.modname}:{recv.id}",
                     f"{op.fn.qualname}.{recv.id}"):
            if cand in tg.lock_kinds:
                return tg.lock_kinds[cand] == "Condition"
    return False


def _check_302(ctx) -> List[Finding]:
    tg = ctx.threadgraph
    pairs = tg.lock_pairs()
    findings: List[Finding] = []
    seen = set()
    for (a, b), (fq, line) in sorted(pairs.items()):
        fn = tg.graph.functions.get(fq)
        path = fn.path if fn else "?"
        if a == b:
            if tg.lock_kinds.get(a) == "Lock":
                findings.append(Finding(
                    path=path, line=line, code="PFX302",
                    message=(
                        f"`{_short(a)}` is acquired while already "
                        f"held (directly or through a helper only "
                        f"called with it held) — a non-reentrant "
                        f"Lock self-deadlocks here; use RLock or "
                        f"hoist the lock out of the helper"),
                    key=f"reacquire:{a}"))
            continue
        if (b, a) not in pairs or (b, a) in seen:
            continue
        seen.add((a, b))
        ofq, oline = pairs[(b, a)]
        ofn = tg.graph.functions.get(ofq)
        findings.append(Finding(
            path=path, line=line, code="PFX302",
            message=(
                f"inconsistent lock order: `{_short(a)}` is held "
                f"while acquiring `{_short(b)}` here, but "
                f"{ofn.path if ofn else '?'}:{oline} acquires "
                f"`{_short(a)}` while holding `{_short(b)}` — two "
                f"threads on these paths deadlock; pick one global "
                f"order"),
            key=f"order:{min(a, b)}<>{max(a, b)}"))
    return findings


def _check_303(ctx) -> List[Finding]:
    tg = ctx.threadgraph
    findings: List[Finding] = []
    emitted = set()
    # functions with a direct blocking call, for the one-level check
    direct_block = {}
    for op in tg.calls:
        what = _blocking_what(op)
        if what and not _is_condition_wait(tg, op):
            direct_block.setdefault(op.fn.qualname, (what, op.lineno))
    for op in tg.calls:
        if not op.locks:
            continue
        what = _blocking_what(op)
        if what and not _is_condition_wait(tg, op):
            fkey = (op.fn.qualname, what)
            if fkey in emitted:
                continue
            emitted.add(fkey)
            findings.append(Finding(
                path=op.fn.path, line=op.lineno, code="PFX303",
                message=(
                    f"blocking call {what} while holding "
                    f"`{_lock_list(op.locks)}` — move the wait out "
                    f"of the lock region (snapshot under the lock, "
                    f"block outside it)"),
                key=f"{op.fn.qualname}:{what}"))
            continue
        # one level deep: a locked call into a blocking helper
        for t in op.targets:
            hit = direct_block.get(t)
            if hit is None:
                continue
            inner_what, inner_line = hit
            fkey = (op.fn.qualname, t, inner_what)
            if fkey in emitted:
                continue
            emitted.add(fkey)
            tinfo = tg.graph.functions.get(t)
            findings.append(Finding(
                path=op.fn.path, line=op.lineno, code="PFX303",
                message=(
                    f"call into `{t.split(':', 1)[-1]}` while "
                    f"holding `{_lock_list(op.locks)}` blocks: it "
                    f"calls {inner_what} at "
                    f"{tinfo.path if tinfo else '?'}:{inner_line} — "
                    f"release the lock before the call"),
                key=f"{op.fn.qualname}->{t}:{inner_what}"))
    return findings


def _lock_list(locks) -> str:
    return ", ".join(sorted(_short(k) for k in locks))


def check(ctx) -> List[Finding]:
    return _check_302(ctx) + _check_303(ctx)
