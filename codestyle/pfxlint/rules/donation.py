"""PFX104 — use-after-donation of a jit argument buffer.

``jax.jit(f, donate_argnums=(0,))`` tells XLA it may reuse the
argument's device buffer for the outputs. Reading that Python
reference AFTER the call touches a deleted buffer and raises (or, on
some backends, silently reads garbage). The safe idiom rebinds the
donated reference from the call's own result::

    state, metrics = self._train_step(state, batch)   # fine
    loss = self._train_step(state, batch)             # state donated
    print(state.step)                                 # PFX104

Detection: every ``jax.jit(fn, donate_argnums=...)`` /
``donate_argnames=...`` wrapping is recorded against wherever the
wrapped callable is stored (``self._train_step``, a module global, a
local) or against the decorated function itself. At each call site
the donated positions map to the argument expressions; a donated
``name`` / ``self.attr`` argument read later in the SAME function
body — with no rebind in between — is flagged. A rebind on the
statement that makes the call (tuple targets included) counts as at
the call line.

Known-unsound: reads that lexically precede the call but execute
after it on a loop back-edge are missed (the analysis is
line-ordered); donated buffers escaping through other aliases are
missed. Both are documented in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import _dotted_from
from ..engine import Finding
from . import own_nodes

CODES = ("PFX104",)

_JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}


def _expr_token(expr: ast.AST) -> Optional[str]:
    """A stable token for a donatable reference: bare name or a
    ``self.attr`` chain."""
    d = _dotted_from(expr)
    if d is None:
        return None
    parts = d.split(".")
    if len(parts) == 1 or parts[0] in ("self", "cls"):
        return d
    return None


def _donations_from_call(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(donated positions, donated names) from jit kwargs."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and \
                        isinstance(c.value, int):
                    nums.add(c.value)
        elif kw.arg == "donate_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and \
                        isinstance(c.value, str):
                    names.add(c.value)
    return nums, names


def _jit_donation(ctx, fn, value: ast.AST
                  ) -> Optional[Tuple[Set[int], Set[str],
                                      Optional[str]]]:
    """``jax.jit(inner, donate_*=...)`` -> (nums, names, inner
    qualname or None)."""
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted_from(value.func)
    if dotted is None:
        return None
    mod = ctx.callgraph.modules.get(fn.modname) if fn else None
    gdot = ctx.callgraph.resolve_dotted(mod, dotted) if mod else dotted
    if gdot not in _JIT_NAMES:
        return None
    nums, names = _donations_from_call(value)
    if not nums and not names:
        return None
    inner = None
    if value.args:
        hit = ctx.callgraph._resolve_fn_arg(mod, fn, value.args[0])
        if hit is not None:
            inner = hit.qualname
    return nums, names, inner


def _positions_for(ctx, inner_qual: Optional[str], nums: Set[int],
                   names: Set[str]) -> Tuple[Set[int], Set[str]]:
    """Fold donate_argnames into positions via the wrapped function's
    param list when it resolved."""
    if not names or inner_qual is None:
        return nums, names
    info = ctx.callgraph.functions.get(inner_qual)
    if info is None:
        return nums, names
    params = [p for p in info.params if p not in ("self", "cls")]
    out = set(nums)
    left = set(names)
    for n in list(left):
        if n in params:
            out.add(params.index(n))
            left.discard(n)
    return out, left


def _collect_donors(ctx) -> Dict[Tuple[str, str],
                                 Tuple[Set[int], Set[str]]]:
    """(function qualname, callee token) -> donated (positions,
    keyword names). The token is how call sites name the donor:
    ``self._train_step``, a bare local name, or a module global."""
    donors: Dict[Tuple[str, str], Tuple[Set[int], Set[str]]] = {}
    cg = ctx.callgraph
    for fq, fn in cg.functions.items():
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            got = _jit_donation(ctx, fn, node.value)
            if got is None:
                continue
            nums, names, inner = got
            nums, names = _positions_for(ctx, inner, nums, names)
            for tgt in node.targets:
                tok = _expr_token(tgt)
                if tok is None:
                    continue
                if tok.startswith("self.") or tok.startswith("cls."):
                    # methods of the same class call it as self.X
                    scope = fn.class_name or ""
                    donors[(f"{fn.modname}|{scope}", tok)] = \
                        (nums, names)
                else:
                    donors[(fq, tok)] = (nums, names)
                    donors[(f"{fn.modname}|", tok)] = (nums, names)
    # decorated form: @partial(jax.jit, donate_argnums=...) etc. is
    # rooted by callgraph already; here handle the direct decorator
    for fq, fn in cg.functions.items():
        for deco in getattr(fn.node, "decorator_list", []):
            if isinstance(deco, ast.Call):
                got = _jit_donation(ctx, fn, deco)
                if got is None:
                    # @partial(jax.jit, donate_argnums=...)
                    got = _partial_jit_donation(ctx, fn, deco)
                if got is None:
                    continue
                nums, names, _ = got
                params = [p for p in fn.params
                          if p not in ("self", "cls")]
                pos = set(nums)
                for n in names:
                    if n in params:
                        pos.add(params.index(n))
                donors[(f"{fn.modname}|", fn.node.name)] = (pos, names)
                if fn.class_name:
                    donors[(f"{fn.modname}|{fn.class_name}",
                            f"self.{fn.node.name}")] = (pos, names)
    return donors


def _partial_jit_donation(ctx, fn, deco: ast.Call):
    """``@functools.partial(jax.jit, donate_argnums=...)``."""
    dotted = _dotted_from(deco.func)
    mod = ctx.callgraph.modules.get(fn.modname)
    if dotted is None or mod is None:
        return None
    if ctx.callgraph.resolve_dotted(mod, dotted) not in (
            "functools.partial", "partial"):
        return None
    if not deco.args:
        return None
    inner_dot = _dotted_from(deco.args[0])
    if inner_dot is None or \
            ctx.callgraph.resolve_dotted(mod, inner_dot) not in \
            _JIT_NAMES:
        return None
    nums, names = _donations_from_call(deco)
    if not nums and not names:
        return None
    return nums, names, None


def _rebind_lines(fn, token: str) -> List[int]:
    """Lines where ``token`` is (re)assigned inside the function."""
    out = []
    for node in own_nodes(fn.node):
        tgts = []
        if isinstance(node, ast.Assign):
            tgts = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                               ast.NamedExpr)):
            tgts = [node.target]
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            tgts = [node.target]
        for t in tgts:
            for part in ast.walk(t):
                if _expr_token(part) == token:
                    out.append(node.lineno)
    return out


def _read_lines(fn, token: str) -> List[int]:
    """Lines where ``token`` is READ inside the function."""
    out = []
    for node in own_nodes(fn.node):
        if isinstance(node, (ast.Name, ast.Attribute)) and \
                isinstance(getattr(node, "ctx", None), ast.Load) and \
                _expr_token(node) == token:
            out.append(node.lineno)
    return out


def check(ctx) -> List[Finding]:
    """PFX104 at every call site of a donating jit wrapper.

    Args:
        ctx: the lint context (call graph already built).

    Returns:
        One finding per donated argument still read after the call.
    """
    donors = _collect_donors(ctx)
    if not donors:
        return []
    findings: List[Finding] = []
    cg = ctx.callgraph
    for fq, fn in cg.functions.items():
        scope_keys = [fq, f"{fn.modname}|",
                      f"{fn.modname}|{fn.class_name or ''}"]
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            tok = _expr_token(node.func)
            if tok is None:
                continue
            spec = None
            for sk in scope_keys:
                spec = donors.get((sk, tok))
                if spec is not None:
                    break
            if spec is None:
                continue
            nums, kwnames = spec
            donated_exprs: List[ast.AST] = []
            for i, arg in enumerate(node.args):
                if i in nums:
                    donated_exprs.append(arg)
            for kw in node.keywords:
                if kw.arg and kw.arg in kwnames:
                    donated_exprs.append(kw.value)
            for arg in donated_exprs:
                atok = _expr_token(arg)
                if atok is None:
                    continue
                call_line = node.lineno
                end_line = node.end_lineno or call_line
                rebinds = sorted(
                    ln for ln in _rebind_lines(fn, atok)
                    if ln >= call_line)
                next_rebind = rebinds[0] if rebinds else None
                for rl in _read_lines(fn, atok):
                    if rl <= end_line:
                        continue
                    if next_rebind is not None and rl > next_rebind:
                        continue
                    if next_rebind is not None and \
                            next_rebind <= call_line and \
                            next_rebind <= end_line:
                        break   # rebound by the call statement itself
                    findings.append(Finding(
                        path=fn.path, line=rl, code="PFX104",
                        message=(
                            f"`{atok}` was donated to `{tok}` at "
                            f"line {call_line} (donate_argnums) — "
                            f"its device buffer may already be "
                            f"reused; rebind it from the call's "
                            f"result before reading it"),
                        key=f"{fq}:{atok}->{tok}"))
                    break   # one finding per donated arg per call
    return findings
