"""PFX304 — a thread entrypoint without a timeline track.

Every ``threading.Thread``/``Timer`` target the thread graph
enumerates (``threadgraph.thread_roots``) is a long-lived flow of
wall-clock time the per-thread timeline
(``paddlefleetx_tpu/observability/timeline.py``) exists to attribute.
A spawned entrypoint that never registers a track is a blind spot:
its time shows up nowhere in the ``/timeline`` view or the Perfetto
export, and the fleet ``overlap_ratio`` silently under-counts. The
rule walks the resolved call closure of each ``thread:`` root looking
for a reachable ``timeline.track(...)`` /
``ThreadTimeline.track(...)`` call and fires on roots that never get
there.

HTTP-handler contexts (``http:`` roots — every method of a
``BaseHTTPRequestHandler`` subclass) are exempt: per-request threads
are covered by instrumenting the shared dispatch path (the metrics
server's ``_handle`` registers the ``pfx-metrics`` track), and
holding every tiny ``do_GET``/``log_message`` override to a
registration of its own would be noise, not coverage.

The finding anchors on the root function's ``def`` line; its stable
key is the root qualname, so the fingerprint survives edits that move
the function.
"""

from __future__ import annotations

from typing import List, Set

from ..engine import Finding

CODES = ("PFX304",)

#: function-name suffixes (after the ``mod:`` split) that register a
#: timeline track
_TRACK_FNS = {"track", "ThreadTimeline.track"}


def _is_track_call(qual: str) -> bool:
    """Whether a resolved callee qualname is the timeline module's
    track registration (matched by module basename so the in-memory
    fixture trees of the test suite count too)."""
    if ":" not in qual:
        return False
    mod, name = qual.split(":", 1)
    return mod.rsplit(".", 1)[-1] == "timeline" and name in _TRACK_FNS


def _reaches_track(tg, root: str) -> bool:
    """BFS over the resolved call edges from ``root``."""
    seen: Set[str] = {root}
    stack = [root]
    while stack:
        qual = stack.pop()
        for nxt in tg._edges(qual):
            if _is_track_call(nxt):
                return True
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def check(ctx) -> List[Finding]:
    """PFX304 over every ``thread:`` root the thread graph found.

    Args:
        ctx: the lint context (thread graph already built).

    Returns:
        One finding per uninstrumented thread entrypoint.
    """
    tg = ctx.threadgraph
    findings: List[Finding] = []
    for root, label in sorted(tg.thread_roots.items()):
        if not label.startswith("thread:"):
            continue
        if _reaches_track(tg, root):
            continue
        fn = ctx.callgraph.functions.get(root)
        if fn is None:
            continue
        findings.append(Finding(
            path=fn.path, line=fn.node.lineno, code="PFX304",
            message=(
                f"thread entrypoint `{root.split(':', 1)[1]}` never "
                f"registers a timeline track — call "
                f"`observability.timeline.track(<name>)` at loop "
                f"start so the thread's time is attributable "
                f"(docs/observability.md, Thread timeline)"),
            key=root))
    return findings
