"""PFX101 — host synchronization inside jit-reachable code.

A host sync inside a traced function either crashes at trace time
(``np.asarray`` on a tracer, ``float()`` on a tracer) or — worse —
silently serializes the device pipeline every step
(``.block_until_ready()``, ``jax.device_get``, ``.item()`` on a
concrete array captured by closure). The GSPMD serving/training model
this repo is built on (one program admitted from the host, PAPERS
2105.04663) forbids all of them past the jit boundary.

Flagged inside any function the call graph marks jit-reachable:

- ``x.item()`` / ``x.block_until_ready()`` method calls;
- ``jax.device_get(...)`` / ``jax.block_until_ready(...)``;
- ``np.asarray`` / ``np.array`` / ``np.frombuffer`` on a non-literal
  argument (literal lists/tuples are trace-time constants and fine);
- ``float(x)`` / ``int(x)`` / ``bool(x)`` where ``x`` mentions a
  tracer-typed parameter (sound for directly-jitted functions whose
  non-static params ARE tracers; annotation-gated otherwise) — shape
  arithmetic is exempt (``.shape`` / ``.ndim`` / ``len()`` uses).
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding
from . import own_nodes, resolve_call

CODES = ("PFX101",)

_NP_MATERIALIZE = {"numpy.asarray", "numpy.array", "numpy.frombuffer"}
_JAX_SYNC = {"jax.device_get", "jax.block_until_ready"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    return False


def _mentions_tracer(expr: ast.AST, tracer_params) -> bool:
    """Whether a cast argument references a tracer param OUTSIDE
    shape/len context (``int(x.shape[0])`` is static, ``int(x)`` is a
    sync)."""
    exempt = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and \
                node.attr in _SHAPE_ATTRS and \
                isinstance(node.value, ast.Name):
            exempt.add(id(node.value))
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "len":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        exempt.add(id(sub))
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in tracer_params \
                and id(node) not in exempt:
            return True
    return False


def check(ctx) -> List[Finding]:
    """Scan every jit-reachable function for host-sync hazards."""
    findings: List[Finding] = []

    def add(fn, node, what):
        findings.append(Finding(
            fn.path, node.lineno, "PFX101",
            f"host sync `{what}` inside jit-reachable "
            f"`{fn.qualname.split(':', 1)[1]}` "
            f"(traced via: {fn.traced_via})",
            key=f"{fn.qualname}:{what}"))

    for fn in ctx.callgraph.reachable_functions():
        tracers = fn.tracer_params
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "item" and not node.args:
                    add(fn, node, ".item()")
                    continue
                if func.attr == "block_until_ready":
                    add(fn, node, ".block_until_ready()")
                    continue
            gdot = resolve_call(ctx, fn, node)
            if gdot in _JAX_SYNC:
                add(fn, node, gdot)
            elif gdot in _NP_MATERIALIZE:
                if node.args and not _is_literal(node.args[0]):
                    add(fn, node, gdot)
            elif isinstance(func, ast.Name) and \
                    func.id in _CAST_BUILTINS and len(node.args) == 1:
                if _mentions_tracer(node.args[0], tracers):
                    add(fn, node, f"{func.id}() on tracer")
    return findings
