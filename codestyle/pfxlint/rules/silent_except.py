"""PFX206 — no silently swallowed exceptions in ``core/``.

The resilience contract (docs/robustness.md): a failure either
propagates or leaves a trace. An ``except ...: pass`` in the training
engine, checkpoint layer, or serving loop turns a real fault into
silence — the exact failure mode the crash-surviving flight recorder
exists to prevent — and a bare ``except:`` additionally eats
``KeyboardInterrupt``/``SystemExit``.

The rule, scoped to ``paddlefleetx_tpu/core/``:

- an ``except`` handler whose body is only ``pass``/``...`` is flagged
  unless the try sits in dead-obviously-intentional company: the
  handler carries a logger/recorder call (impossible for a pass-only
  body) — i.e. pass-only handlers always need an explanatory
  suppression (``# pfxlint: disable=PFX206`` with a justification
  comment);
- a bare ``except:`` (no exception type) is flagged unless its body
  re-``raise``s or makes a logging/recorder call (``logger.*``,
  ``warnings.warn``, ``.emit``).

Handlers that RETURN a sentinel (``except X: return None``) or raise
a translated error are the legitimate narrow-except idiom and are not
flagged.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding

CODES = ("PFX206",)

_SCOPE_PREFIX = "paddlefleetx_tpu/core/"

#: attribute/function names whose call inside a handler counts as
#: leaving a trace (logging, flight-recorder emit, warnings.warn)
_TRACE_CALLS = {"debug", "info", "warning", "error", "exception",
                "critical", "log", "emit", "warn", "print"}


def _leaves_trace(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr in _TRACE_CALLS:
                return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _body_is_noop(handler: ast.ExceptHandler) -> bool:
    """True when the handler body does nothing: only ``pass`` and/or
    bare constant expressions (``...``, docstrings)."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


def _type_label(handler: ast.ExceptHandler) -> str:
    if handler.type is None:
        return "bare"
    return ast.unparse(handler.type)


def check(ctx) -> List[Finding]:
    """Flag silent exception swallowing under ``core/``."""
    findings: List[Finding] = []
    for src in ctx.py_files:
        if not src.path.startswith(_SCOPE_PREFIX):
            continue
        seen: dict = {}   # (qual-ish key) -> ordinal, for stable keys
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            label = _type_label(node)
            noop = _body_is_noop(node)
            bare_silent = node.type is None and \
                not (_leaves_trace(node) or _reraises(node))
            if not (noop or bare_silent):
                continue
            ordinal = seen.get(label, 0)
            seen[label] = ordinal + 1
            key = f"{label}:{ordinal}"
            if noop:
                msg = (f"`except {label}: pass` silently swallows the "
                       f"exception — log it, emit a recorder event, "
                       f"or suppress with a justification "
                       f"(docs/robustness.md)")
                if label == "bare":
                    msg = ("bare `except:` with an empty body swallows "
                           "EVERYTHING, KeyboardInterrupt included — "
                           "narrow the type and leave a trace")
            else:
                msg = (f"bare `except:` without a log/recorder call or "
                       f"re-raise — it hides the failure AND catches "
                       f"KeyboardInterrupt/SystemExit; narrow the "
                       f"type or leave a trace")
            findings.append(Finding(
                src.path, node.lineno, "PFX206", msg, key=key))
    return findings
