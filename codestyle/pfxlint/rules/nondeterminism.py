"""PFX102 — wall-clock / ambient-randomness reads in traced code.

A traced function runs ONCE per compiled shape; whatever
``time.time()`` or ``np.random.normal()`` returned during that trace
is baked into the program as a constant and silently reused every
step — and two hosts tracing the same SPMD program bake DIFFERENT
constants, which is how multi-process runs deadlock or diverge.
Randomness belongs to explicit ``jax.random`` keys (which the rule
never flags: ``from jax import random`` resolves to ``jax.random.*``
through the alias table, not to the stdlib module).
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding
from . import own_nodes, resolve_call

CODES = ("PFX102",)

#: exact callables, resolved through imports
_EXACT = {
    "os.urandom", "uuid.uuid1", "uuid.uuid4",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

#: resolved-name prefixes that are nondeterministic wholesale
_PREFIXES = (
    "time.", "numpy.random.", "random.", "secrets.",
)


def check(ctx) -> List[Finding]:
    """Scan every jit-reachable function for ambient nondeterminism."""
    findings: List[Finding] = []
    for fn in ctx.callgraph.reachable_functions():
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            gdot = resolve_call(ctx, fn, node)
            if gdot is None:
                continue
            if gdot in _EXACT or gdot.startswith(_PREFIXES):
                findings.append(Finding(
                    fn.path, node.lineno, "PFX102",
                    f"nondeterministic `{gdot}` inside jit-reachable "
                    f"`{fn.qualname.split(':', 1)[1]}` — its value is "
                    f"baked in at trace time "
                    f"(traced via: {fn.traced_via})",
                    key=f"{fn.qualname}:{gdot}"))
    return findings
