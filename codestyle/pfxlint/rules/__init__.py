"""The pfxlint rule registry and shared AST helpers.

Every rule module exposes ``CODES`` (tuple of rule ids it can emit)
and ``check(ctx) -> list[Finding]``. Registration is explicit — the
ordered ``ALL_RULES`` list below — so output ordering and rule
documentation (``docs/static_analysis.md``) stay in lockstep. The
shared helpers are defined BEFORE the submodule imports at the bottom
because the submodules import them back from this package.
"""

from __future__ import annotations

import ast
from typing import Iterator


def own_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Yield the nodes belonging to ONE function body, skipping
    nested function/class definitions (they are separate call-graph
    entries with their own reachability); lambdas are kept — they run
    inline under the same trace and are not indexed separately."""
    stack = list(getattr(fn_node, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def resolve_call(ctx, fn, call: ast.Call):
    """Resolved global dotted name of a call's callee, or None."""
    from ..callgraph import _dotted_from
    dotted = _dotted_from(call.func)
    if dotted is None:
        return None
    mod = ctx.callgraph.modules.get(fn.modname)
    if mod is None:
        return dotted
    return ctx.callgraph.resolve_dotted(mod, dotted)


from . import (counters, docstrings, donation, fallbacks,   # noqa: E402
               host_sync, knobs, locks, nondeterminism, races,
               silent_except, timeline, tracer_branch, tracer_escape)

#: ordered registry; docs/static_analysis.md mirrors this table
ALL_RULES = [
    host_sync, nondeterminism, tracer_branch,
    donation, tracer_escape,
    races, locks, timeline,
    counters, knobs, fallbacks, silent_except, docstrings,
]


def rule_codes() -> list:
    """Every rule id pfxlint can emit, in registry order."""
    out = []
    for mod in ALL_RULES:
        out.extend(mod.CODES)
    return out
