"""PFX205 — Pallas kernel call sites carry a fallback + counter.

The kernel-dispatch contract every matrix documents
(``docs/attention_dispatch.md``, ``docs/moe.md``): production code
never calls a Pallas kernel bare. The dispatch site wraps the call in
``try/except (ImportError, NotImplementedError)`` so kernel admission
failure degrades to the XLA path instead of crashing the step, and it
increments a trace-time dispatch counter so telemetry can attest
which lowering actually ran (``ops/attention.py::
dot_product_attention`` and ``models/gpt/moe.py`` are the reference
sites).

The rule: any call that resolves into ``paddlefleetx_tpu.ops.pallas.*``
from OUTSIDE ``ops/pallas/`` (the kernel modules themselves are the
kernel, and scripts/benches exercising kernels directly are out of
scope) must sit lexically inside a ``try`` with at least one handler,
in a function that also registers at least one ``metrics`` series.

Only calls whose target transitively launches a kernel (reaches a
``pl.pallas_call`` through the in-tree call graph) count: admission
probes like ``check_shapes`` raise ``NotImplementedError`` without
ever touching the hardware, and callers legitimately hoist them ahead
of the dispatch decision (``ops/ring_attention.py::_flash_block_ok``).
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding
from . import resolve_call

CODES = ("PFX205",)

_KERNEL_NS = "paddlefleetx_tpu.ops.pallas."
_SCOPE_PREFIX = "paddlefleetx_tpu/"
_EXEMPT_PREFIX = "paddlefleetx_tpu/ops/pallas/"
_REGISTER_ATTRS = {"inc", "set_gauge", "add_time", "timer"}


def _has_metric_registration(fn_node: ast.AST) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr in _REGISTER_ATTRS:
                return True
    return False


def _launches_kernel(ctx, gdot: str, cache: dict) -> bool:
    """True when the in-tree function named ``gdot`` transitively
    reaches a ``pallas_call``; True too when it cannot be resolved
    in-tree (conservative — an unresolvable target in the kernel
    namespace is assumed to launch)."""
    cg = ctx.callgraph
    if gdot in cache:
        return cache[gdot]
    cache[gdot] = False          # cycle guard: in-flight -> no launch
    fn = cg._function_for_global(gdot)
    if fn is None:
        cache[gdot] = True
        return True
    mod = cg.modules.get(fn.modname)
    result = False
    for ref in fn.calls:
        if ref.dotted is None or ref.is_self:
            continue
        g = cg.resolve_dotted(mod, ref.dotted) if mod else ref.dotted
        if g.split(".")[-1] == "pallas_call":
            result = True
            break
        if mod is not None and "." not in g and g in mod.functions:
            g = f"{fn.modname}.{g}"   # same-module bare-name call
        if cg._function_for_global(g) is not None and \
                _launches_kernel(ctx, g, cache):
            result = True
            break
    cache[gdot] = result
    return result


def _walk(node, in_try, fn, ctx, cache):
    """Yield ``(call, in_try)`` for kernel-launching calls under
    ``node``, tracking whether each sits inside a handled ``try``."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    if isinstance(node, ast.Try):
        handled = bool(node.handlers)
        for child in node.body:
            yield from _walk(child, in_try or handled, fn, ctx, cache)
        for part in (node.handlers, node.orelse, node.finalbody):
            for child in part:
                yield from _walk(child, in_try, fn, ctx, cache)
        return
    if isinstance(node, ast.Call):
        gdot = resolve_call(ctx, fn, node)
        if gdot and gdot.startswith(_KERNEL_NS) and \
                _launches_kernel(ctx, gdot, cache):
            yield node, in_try
    for child in ast.iter_child_nodes(node):
        yield from _walk(child, in_try, fn, ctx, cache)


def check(ctx) -> List[Finding]:
    """Verify every out-of-kernel Pallas call is guarded + counted."""
    findings: List[Finding] = []
    launch_cache: dict = {}
    for fn in ctx.callgraph.functions.values():
        if not fn.path.startswith(_SCOPE_PREFIX) or \
                fn.path.startswith(_EXEMPT_PREFIX):
            continue
        counted = None   # lazy: only computed when a kernel call hits
        for call, in_try in _walk_fn(fn, ctx, launch_cache):
            name = _callee_label(call)
            if not in_try:
                findings.append(Finding(
                    fn.path, call.lineno, "PFX205",
                    f"Pallas kernel call `{name}` outside a "
                    f"try/except fallback in "
                    f"`{fn.qualname.split(':', 1)[1]}` — wrap it so "
                    f"kernel rejection degrades to the XLA path "
                    f"(see ops/attention.py)",
                    key=f"{fn.qualname}:{name}:try"))
            if counted is None:
                counted = _has_metric_registration(fn.node)
            if not counted:
                findings.append(Finding(
                    fn.path, call.lineno, "PFX205",
                    f"Pallas kernel call `{name}` in "
                    f"`{fn.qualname.split(':', 1)[1]}` has no dispatch "
                    f"counter in the enclosing function — telemetry "
                    f"cannot attest this lowering (docs/"
                    f"observability.md, Dispatch counters)",
                    key=f"{fn.qualname}:{name}:counter"))
    return findings


def _walk_fn(fn, ctx, cache):
    for stmt in fn.node.body:
        yield from _walk(stmt, False, fn, ctx, cache)


def _callee_label(call: ast.Call) -> str:
    from ..callgraph import _dotted_from
    return _dotted_from(call.func) or "<kernel>"
