"""PFX201/PFX202 — dispatch-counter names vs the docs matrices.

The repo's observability contract (PR 3 onward): every trace-time
dispatch counter, gauge or timer registered from ``paddlefleetx_tpu/``
appears — by exact name — in a docs matrix (`docs/attention_dispatch
.md`, `docs/moe.md`, `docs/inference.md`, `docs/tensor_parallel.md`,
`docs/observability.md`), and every name the docs promise exists in
code. Review kept this honest for five PRs; this rule makes it
mechanical in both directions:

- **PFX201** — a series name ``inc``'d / ``set_gauge``'d /
  ``timer``'d / ``add_time``'d / ``observe``'d in code — or a SPAN
  name opened via ``start_trace`` / ``start_span`` / ``span_point`` /
  ``complete_span`` — but absent from every docs file. Anchored at
  the first code site.
- **PFX202** — a docs-promised name (in a namespace code actually
  uses) with no code site: stale docs. Anchored at the docs line.

Name extraction understands the in-tree idioms: plain string
constants, the two-way ``IfExp`` dispatch
(``"a/x" if flag else "a/y"``), and prefix concatenation
(``inc("moe/config/" + mode)`` — recorded as a ``moe/config/*``
wildcard satisfied by any documented name under the prefix). Docs
names use the matrices' ``ns/{a,b,c}`` brace shorthand (expanded) —
glob rows like ``serving/*`` are prose cross-references and count for
NEITHER direction, so deleting a concrete docs row always trips
PFX201 regardless of a surviving glob mention. ``timer(X)`` also
registers the implicit ``X/calls`` series; those are docs-optional
but resolve a documented ``X/calls`` row.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..engine import Finding

CODES = ("PFX201", "PFX202")

#: code files whose registrations feed the contract
_CODE_PREFIX = "paddlefleetx_tpu/"
#: the registry/tracer implementations themselves register nothing
_EXEMPT_FILES = {"paddlefleetx_tpu/observability/metrics.py",
                 "paddlefleetx_tpu/observability/spans.py"}

#: histogram observe() joined in PR 10 — same exact-name contract
_REGISTER_ATTRS = {"inc", "set_gauge", "add_time", "timer", "observe"}
#: span-name call sites (observability/spans.py) hold the same
#: docs contract: every span/trace/point name is a docs matrix row;
#: `_phase` is the serving loop's phase-transition wrapper (its name
#: argument is positional arg 1, so span attrs scan EVERY positional
#: arg, not just the first)
_SPAN_ATTRS = {"start_trace", "start_span", "span_point",
               "complete_span", "_phase"}
_NAME_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_]+)+$")
_PREFIX_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_]+)*/$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")
_DOC_TOKEN_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_{},*]+)+$")


def _expand_braces(token: str) -> List[str]:
    """``a/{x,y}/b`` -> ``["a/x/b", "a/y/b"]`` (recursive)."""
    m = re.search(r"\{([^{}]*)\}", token)
    if not m:
        return [token]
    out: List[str] = []
    for alt in m.group(1).split(","):
        out.extend(_expand_braces(
            token[:m.start()] + alt + token[m.end():]))
    return out


def _code_registrations(ctx) -> Tuple[
        Dict[str, Tuple[str, int]], Dict[str, Tuple[str, int]],
        Dict[str, Tuple[str, int]]]:
    """Scan the package for series registrations.

    Returns:
        ``(exact, prefixes, synthetic)`` dicts of name -> first
        ``(path, line)`` site; ``synthetic`` holds the implicit
        ``<timer>/calls`` names (docs-optional).
    """
    exact: Dict[str, Tuple[str, int]] = {}
    prefixes: Dict[str, Tuple[str, int]] = {}
    synthetic: Dict[str, Tuple[str, int]] = {}

    def record(table, name, sf, node):
        table.setdefault(name, (sf.path, node.lineno))

    for sf in ctx.py_files:
        if not sf.path.startswith(_CODE_PREFIX) or \
                sf.path in _EXEMPT_FILES:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) \
                else (func.id if isinstance(func, ast.Name) else None)
            if attr not in _REGISTER_ATTRS and \
                    attr not in _SPAN_ATTRS:
                continue
            scan = node.args if attr in _SPAN_ATTRS \
                else node.args[:1]
            for arg in scan:
                for c in ast.walk(arg):
                    if not (isinstance(c, ast.Constant)
                            and isinstance(c.value, str)):
                        continue
                    if _NAME_RE.match(c.value):
                        record(exact, c.value, sf, node)
                        if attr == "timer":
                            record(synthetic, c.value + "/calls",
                                   sf, node)
                    elif _PREFIX_RE.match(c.value) \
                            and "/" in c.value[:-1]:
                        record(prefixes, c.value, sf, node)
    return exact, prefixes, synthetic


def _doc_names(ctx) -> Dict[str, Tuple[str, int]]:
    """Exact (brace-expanded, non-glob) series names promised by the
    docs, name -> first ``(path, line)``."""
    out: Dict[str, Tuple[str, int]] = {}
    for doc in ctx.docs:
        for lineno, line in enumerate(doc.lines, 1):
            for tok in _BACKTICK_RE.findall(line):
                if not _DOC_TOKEN_RE.match(tok):
                    continue
                if "*" in tok:
                    continue   # glob: prose cross-reference only
                for name in _expand_braces(tok):
                    if _NAME_RE.match(name):
                        out.setdefault(name, (doc.path, lineno))
    return out


def check(ctx) -> List[Finding]:
    """Cross-check code registrations against the docs matrices."""
    exact, prefixes, synthetic = _code_registrations(ctx)
    documented = _doc_names(ctx)
    findings: List[Finding] = []

    # PFX201: code name with no docs row
    for name, (path, line) in sorted(exact.items()):
        if name not in documented:
            findings.append(Finding(
                path, line, "PFX201",
                f"telemetry series `{name}` is registered here but "
                f"appears in no docs matrix (docs/*.md) — add a row "
                f"or rename to a documented series",
                key=name))
    for prefix, (path, line) in sorted(prefixes.items()):
        if not any(d.startswith(prefix) for d in documented):
            findings.append(Finding(
                path, line, "PFX201",
                f"telemetry prefix `{prefix}*` is registered here "
                f"but no documented series falls under it",
                key=prefix + "*"))

    # PFX202: docs row with no code site, within code's namespaces
    namespaces = {n.split("/", 1)[0] for n in exact} | \
        {p.split("/", 1)[0] for p in prefixes}
    known = set(exact) | set(synthetic)
    for name, (path, line) in sorted(documented.items()):
        if name.split("/", 1)[0] not in namespaces:
            continue
        if name in known:
            continue
        if any(name.startswith(p) for p in prefixes):
            continue
        findings.append(Finding(
            path, line, "PFX202",
            f"docs promise telemetry series `{name}` but no code in "
            f"paddlefleetx_tpu/ registers it — stale row or spelling "
            f"drift",
            key=name))
    return findings
