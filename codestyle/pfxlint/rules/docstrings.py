"""D001-D006 — docstring presence/shape, folded in from
``codestyle/docstring_checker.py``.

The standalone checker keeps its own CLI and STRICT tier (D007-D010,
reference-parity, advisory); pfxlint folds in exactly the ENFORCED
tier the old changed-files CI job ran — D001-D006 — and runs it over
the whole tree instead of the diff. One implementation, two front
doors: the rule imports ``check_source`` rather than reimplementing
it, so ``tests/test_docstring_checker.py`` keeps pinning the
semantics for both.
"""

from __future__ import annotations

from typing import List

from ..engine import Finding

CODES = ("D001", "D002", "D003", "D004", "D005", "D006")


def check(ctx) -> List[Finding]:
    """Run the enforced docstring tier over every scanned file."""
    from codestyle import docstring_checker as dc
    findings: List[Finding] = []
    for sf in ctx.py_files:
        for f in dc.check_source(sf.text, sf.path):
            if f.code not in CODES:
                continue
            findings.append(Finding(
                sf.path, f.line, f.code, f.message,
                key=f.message))
    return findings
