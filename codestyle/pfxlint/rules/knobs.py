"""PFX203/PFX204 — every ``PFX_*`` environment knob is documented.

Knobs are the repo's operational API: a bench driver, an SRE, or the
next session discovers ``PFX_BENCH_MAX_HUNG_PROBES`` only if a doc
says it exists. The contract is bidirectional:

- **PFX203** — a ``PFX_*`` name appears as a string literal in code
  (an ``os.environ`` read, a launcher write, a validator set) but in
  no ``docs/*.md``. Anchored at the first code site.
- **PFX204** — a doc mentions a ``PFX_*`` name no code references:
  stale docs. Anchored at the docs line.

Code side: any string constant that IS a knob name (full match) in
any scanned file — reads through loops like
``for var in ("PFX_CACHE_HOME", ...): os.environ.get(var)`` count,
docstrings never match (a docstring is one big string). Docs side:
exact tokens only — ``PFX_BENCH_SERVING_*`` style globs are prose
shorthand and satisfy NEITHER direction, so each knob needs its own
documented line (deleting one line always trips PFX203).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..engine import Finding

CODES = ("PFX203", "PFX204")

_KNOB_RE = re.compile(r"^PFX_[A-Z0-9]+(?:_[A-Z0-9]+)*$")
_DOC_KNOB_RE = re.compile(r"PFX_[A-Z0-9_]+\*?")


def _code_knobs(ctx) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    for sf in ctx.py_files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _KNOB_RE.match(node.value):
                out.setdefault(node.value,
                               (sf.path, node.lineno))
    return out


def _doc_knobs(ctx) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    for doc in ctx.docs:
        for lineno, line in enumerate(doc.lines, 1):
            for tok in _DOC_KNOB_RE.findall(line):
                if tok.endswith("*") or tok.endswith("_"):
                    continue   # glob/prefix shorthand: prose only
                out.setdefault(tok, (doc.path, lineno))
    return out


def check(ctx) -> List[Finding]:
    """Cross-check code knob literals against docs mentions."""
    code = _code_knobs(ctx)
    docs = _doc_knobs(ctx)
    findings: List[Finding] = []
    for knob, (path, line) in sorted(code.items()):
        if knob not in docs:
            findings.append(Finding(
                path, line, "PFX203",
                f"env knob `{knob}` is referenced here but documented "
                f"in no docs/*.md — add it to the knob table "
                f"(docs/observability.md) or docs/quick_start.md",
                key=knob))
    for knob, (path, line) in sorted(docs.items()):
        if knob not in code:
            findings.append(Finding(
                path, line, "PFX204",
                f"docs mention env knob `{knob}` but no code "
                f"references it — stale doc or spelling drift",
                key=knob))
    return findings
