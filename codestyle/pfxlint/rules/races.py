"""PFX301 — unguarded shared-state write across thread contexts.

The classic data race: an instance attribute or module global is
touched from two different thread contexts (main loop + watchdog
thread, main loop + an HTTP scrape thread, ...), at least one of the
conflicting accesses is a write, and the two accesses share NO common
lock. The thread-entry graph (``threadgraph.py``) provides the
context attribution and the per-access held-lock sets (including
locks inherited from always-locked callers).

What does NOT fire:

- accesses inside ``__init__`` / ``__post_init__`` on the object's
  own attributes — they happen-before any thread can hold the object;
- the lock objects themselves (``self._lock`` is shared by design);
- two accesses that can only run on the SAME context;
- guarded pairs: every cross-context conflicting pair shares a lock.

The finding anchors on a write when one is unguarded (that is the
line to wrap in ``with lock:``) and names the witness contexts.
"""

from __future__ import annotations

from typing import Dict, List

from ..engine import Finding

CODES = ("PFX301",)


def _conflicts(a, b, ctx_of) -> bool:
    """Whether two accesses of one key can race: different contexts,
    a write involved, no common lock."""
    if not (a.write or b.write):
        return False
    if a.locks & b.locks:
        return False
    ca, cb = ctx_of(a.fn.qualname), ctx_of(b.fn.qualname)
    if a is b:
        return a.write and len(ca) >= 2 and not a.locks
    for c1 in ca:
        for c2 in cb:
            if c1 != c2:
                return True
    return False


def check(ctx) -> List[Finding]:
    """PFX301 over every shared state key the thread graph recorded.

    Args:
        ctx: the lint context (thread graph already built).

    Returns:
        One finding per racy state key, anchored on an unguarded
        write.
    """
    tg = ctx.threadgraph
    by_key: Dict[str, list] = {}
    for acc in tg.accesses:
        if acc.in_init:
            continue
        by_key.setdefault(acc.key, []).append(acc)

    findings: List[Finding] = []
    for key in sorted(by_key):
        accs = sorted(by_key[key],
                      key=lambda a: (a.fn.path, a.lineno, not a.write))
        hit = None
        for i, a in enumerate(accs):
            for b in accs[i:]:
                if _conflicts(a, b, tg.contexts_of):
                    hit = (a, b)
                    break
            if hit:
                break
        if hit is None:
            continue
        a, b = hit
        # anchor on the unguarded write of the pair when there is one
        anchor = a if (a.write and not a.locks) else \
            (b if (b.write and not b.locks) else (a if a.write else b))
        other = b if anchor is a else a
        ctxs = sorted(tg.contexts_of(anchor.fn.qualname)
                      | tg.contexts_of(other.fn.qualname))
        where = "" if other is anchor else (
            f"; also touched at {other.fn.path}:{other.lineno}"
            f" ({'write' if other.write else 'read'}"
            + (f" under {_lock_names(other.locks)}" if other.locks
               else ", no lock") + ")")
        findings.append(Finding(
            path=anchor.fn.path, line=anchor.lineno, code="PFX301",
            message=(
                f"`{anchor.display}` is "
                f"{'written' if anchor.write else 'read'} without a "
                f"common lock across thread contexts "
                f"{{{', '.join(ctxs)}}}{where} — guard every access "
                f"with one lock or hand the reader an immutable "
                f"snapshot"),
            key=key))
    return findings


def _lock_names(locks) -> str:
    return ", ".join(sorted(k.split(":", 1)[-1] for k in locks))
