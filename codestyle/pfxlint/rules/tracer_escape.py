"""PFX105 — a tracer escapes the trace through self/global/closure.

Inside a jit-traced function every array argument is a tracer — an
abstract value that only means something DURING this trace. Storing
one somewhere that outlives the trace::

    self._last_logits = logits        # on a method under jit
    _CACHE[key] = hidden              # module global
    captured.append(attn)             # closure cell / outer list

leaks it: the next read outside the trace raises
``UnexpectedTracerError`` (or retraces against a stale abstract
value). This is jax's #1 footgun for stateful-looking code migrated
from the eager world (the paper's Paddle layers carry exactly this
kind of member-variable habit).

Flagged in every jit-reachable function, using the call graph's
``tracer_params`` (sound for direct jit roots, annotation-gated for
transitive ones) with linear intraprocedural taint through local
assignments:

- ``self.X = <tainted>`` / ``self.X += <tainted>``;
- a store to a ``global``- or ``nonlocal``-declared name;
- an in-place mutator (``.append`` / ``.update`` / ...) on ``self.X``
  or a global, with a tainted argument.

Shape/dtype projections (``x.shape``, ``len(x)``) launder the taint —
they are concrete at trace time and safe to store.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..engine import Finding
from . import own_nodes

CODES = ("PFX105",)

_SAFE_ATTRS = {"shape", "ndim", "dtype", "size"}

_MUTATORS = {"append", "appendleft", "extend", "insert", "add",
             "update", "setdefault", "put", "put_nowait"}


def _tainted(expr: ast.AST, taint: Set[str]) -> bool:
    """Whether an expression mentions a tainted name outside
    shape/dtype context."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and \
                node.attr in _SAFE_ATTRS:
            continue
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and node.id in taint:
            # laundered when the ONLY use is under a safe attribute
            if not _under_safe_attr(expr, node):
                return True
    return False


def _under_safe_attr(root: ast.AST, name_node: ast.Name) -> bool:
    """Whether ``name_node`` appears as ``name.shape``-style inside
    ``root`` (its direct parent is a safe attribute access)."""
    for node in ast.walk(root):
        if isinstance(node, ast.Attribute) and \
                node.attr in _SAFE_ATTRS and node.value is name_node:
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "len" and \
                    node.args and node.args[0] is name_node:
                return True
    return False


def check(ctx) -> List[Finding]:
    """PFX105 over every jit-reachable function with tracer params.

    Args:
        ctx: the lint context (call graph already built).

    Returns:
        One finding per escaping store, deduplicated by fingerprint.
    """
    findings: List[Finding] = []
    for fn in ctx.callgraph.reachable_functions():
        taint = set(fn.tracer_params)
        if not taint:
            continue
        declared: Set[str] = set()
        for node in own_nodes(fn.node):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared.update(node.names)
        # line-ordered linear pass so taint flows through locals
        stmts = sorted(own_nodes(fn.node),
                       key=lambda n: (getattr(n, "lineno", 0),
                                      getattr(n, "col_offset", 0)))
        for node in stmts:
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                value = node.value
                if value is None or not _tainted(value, taint):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    for leaf in _target_leaves(tgt):
                        if isinstance(leaf, ast.Name):
                            if leaf.id in declared:
                                findings.append(_escape(
                                    fn, node.lineno, leaf.id,
                                    "a global/nonlocal binding"))
                            else:
                                taint.add(leaf.id)
                        elif isinstance(leaf, ast.Attribute) and \
                                _is_selfish(leaf.value):
                            findings.append(_escape(
                                fn, node.lineno,
                                f"self.{leaf.attr}",
                                "an attribute that outlives the "
                                "trace"))
                        elif isinstance(leaf, ast.Subscript):
                            base = leaf.value
                            if isinstance(base, ast.Attribute) and \
                                    _is_selfish(base.value):
                                findings.append(_escape(
                                    fn, node.lineno,
                                    f"self.{base.attr}[...]",
                                    "an attribute that outlives the "
                                    "trace"))
                            elif isinstance(base, ast.Name) and \
                                    base.id in declared:
                                findings.append(_escape(
                                    fn, node.lineno,
                                    f"{base.id}[...]",
                                    "a global container"))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                args_tainted = any(_tainted(a, taint)
                                   for a in node.args) or \
                    any(_tainted(kw.value, taint)
                        for kw in node.keywords)
                if not args_tainted:
                    continue
                recv = node.func.value
                if isinstance(recv, ast.Attribute) and \
                        _is_selfish(recv.value):
                    findings.append(_escape(
                        fn, node.lineno,
                        f"self.{recv.attr}.{node.func.attr}(...)",
                        "an attribute that outlives the trace"))
                elif isinstance(recv, ast.Name) and \
                        recv.id in declared:
                    findings.append(_escape(
                        fn, node.lineno,
                        f"{recv.id}.{node.func.attr}(...)",
                        "a global container"))
    # de-duplicate by fingerprint, keep first line
    seen = set()
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        if f.fingerprint() in seen:
            continue
        seen.add(f.fingerprint())
        out.append(f)
    return out


def _target_leaves(tgt: ast.AST):
    if isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            yield from _target_leaves(e)
    elif isinstance(tgt, ast.Starred):
        yield from _target_leaves(tgt.value)
    else:
        yield tgt


def _is_selfish(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Name) and expr.id in ("self", "cls")


def _escape(fn, line: int, what: str, where: str) -> Finding:
    return Finding(
        path=fn.path, line=line, code="PFX105",
        message=(
            f"tracer-typed value stored into `{what}` — {where}; "
            f"inside a traced function this leaks the tracer and "
            f"raises UnexpectedTracerError on the next read; return "
            f"the value instead of storing it"),
        key=f"{fn.qualname}:{what}")
