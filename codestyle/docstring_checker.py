"""Docstring style checker.

Parity: reference ``codestyle/docstring_checker.py`` (a 349-line
pylint plugin enforcing docstring presence/shape, with its own unit
test — the reference's only unit-tested component, SURVEY §4). pylint
isn't a dependency here, so this is a standalone ``ast``-based checker
with the same rule set:

  D001  module missing docstring
  D002  public class missing docstring
  D003  public function/method missing docstring (> ``max_lines``
        lines; one-liners and private names are exempt)
  D004  docstring does not start with a capital letter or quote
  D005  one-line docstring should end with a period

Run: ``python codestyle/docstring_checker.py <paths...>``.
"""

from __future__ import annotations

import ast
import dataclasses
import sys
from typing import Iterator, List

MAX_UNDOCUMENTED_LINES = 10


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    code: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _is_public(name: str) -> bool:
    return not name.startswith("_")


def _doc_findings(node, doc, path) -> Iterator[Finding]:
    if doc is None:
        return
    stripped = doc.strip()
    if not stripped:
        return
    first = stripped[0]
    if not (first.isupper() or first in "\"'`[(0123456789"):
        yield Finding(path, node.lineno, "D004",
                      "docstring should start with a capital letter")
    if "\n" not in stripped and not stripped.endswith((".", "!", "?",
                                                      ":", "`", ")")):
        yield Finding(path, node.lineno, "D005",
                      "one-line docstring should end with a period")


def check_source(source: str, path: str = "<string>") -> List[Finding]:
    tree = ast.parse(source)
    findings: List[Finding] = []

    mod_doc = ast.get_docstring(tree)
    if mod_doc is None:
        findings.append(Finding(path, 1, "D001",
                                "module missing docstring"))
    else:
        findings.extend(_doc_findings(tree.body[0], mod_doc, path))

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and _is_public(node.name):
            doc = ast.get_docstring(node)
            if doc is None:
                findings.append(Finding(
                    path, node.lineno, "D002",
                    f"public class {node.name!r} missing docstring"))
            else:
                findings.extend(_doc_findings(node, doc, path))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_public(node.name):
            doc = ast.get_docstring(node)
            n_lines = (node.end_lineno or node.lineno) - node.lineno
            if doc is None and n_lines > MAX_UNDOCUMENTED_LINES:
                findings.append(Finding(
                    path, node.lineno, "D003",
                    f"public function {node.name!r} missing docstring"))
            elif doc is not None:
                findings.extend(_doc_findings(node, doc, path))
    return findings


def check_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        return check_source(f.read(), path)


def main(argv=None) -> int:
    import os
    args = argv if argv is not None else sys.argv[1:]
    findings: List[Finding] = []
    for target in args:
        if os.path.isdir(target):
            for root, _dirs, files in os.walk(target):
                findings.extend(
                    f for name in sorted(files) if name.endswith(".py")
                    for f in check_file(os.path.join(root, name)))
        else:
            findings.extend(check_file(target))
    for f in findings:
        print(f)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
