"""Docstring style checker.

Parity: reference ``codestyle/docstring_checker.py`` (a 349-line
pylint plugin enforcing docstring presence/shape, with its own unit
test — the reference's only unit-tested component, SURVEY §4). pylint
isn't a dependency here, so this is a standalone ``ast``-based checker
implementing the reference's rules one for one:

  ==== ========= =====================================================
  ours reference rule
  ==== ========= =====================================================
  D001 (W9005)   module missing docstring
  D002 (W9005)   public class missing docstring
  D003 W9005     public function (> ``MAX_UNDOCUMENTED_LINES`` lines)
                 missing docstring, or docstring shorter than 10 chars
  D004 —         docstring should start with a capital letter (ours)
  D005 W9002     one-line docstring should end with a period
  D006 W9001     short docstring (< 40 chars) spread over > 1 line
  D007 W9006     docstring continuation lines must use 4-space indent
                 (the reference's loop never advances its line counter
                 so its W9006 can never fire; this implements the
                 documented intent)
  D008 W9003     all function args must appear in the ``Args:``
                 section (public functions > 10 lines with a doc)
  D009 W9007     function with a value ``return`` needs ``Returns:``
  D010 W9008     function with a ``raise`` needs ``Raises:``
  ==== ========= =====================================================

Sections are parsed with the reference ``Docstring.parse`` grammar:
``Args/Returns/Raises/Examples`` headers claim the following
deeper-indented lines; ``Args`` entries match
``name (type):`` (reference ``_arg_with_type``).

Run: ``python codestyle/docstring_checker.py <paths...>``.
Pass ``--select D001,D003`` to restrict the rule set.

Tiers: the pre-commit hook enforces D001-D006 (presence + shape —
what this repo's own docstrings hold to). D007-D010 are the
reference-parity STRICT tier, opt-in via ``--select``: the repo's
house style wraps continuation lines at 2 spaces (D007 would flag
it) and documents args in prose rather than ``Args:`` tables
(D008/D009) — the reference never gated CI on its equivalents either
(its W9006 loop never advances its line counter, and the plugin ran
advisory-only).
"""

from __future__ import annotations

import ast
import dataclasses
import re
import sys
from collections import defaultdict
from typing import Iterator, List, Optional

MAX_UNDOCUMENTED_LINES = 10
ONE_LINE_MAX_CHARS = 40          # reference one_line: len(doc) > 40 exempt
MIN_DOC_CHARS = 10               # reference missing_doc_string len < 10


@dataclasses.dataclass
class Finding:
    """One rule violation: ``path:line: code message``."""

    path: str
    line: int
    code: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class Docstring:
    """Parsed docstring sections (reference ``Docstring`` class,
    ``docstring_checker.py:30-109``): ``Args/Returns/Raises/Examples``
    headers claim the deeper-indented lines that follow; ``Args``
    entries are matched as ``name (type):``."""

    _ARG_RE = re.compile(r"([A-Za-z0-9_-]+)\s{0,4}(\(.+\))\s{0,4}:")

    def __init__(self, doc: str):
        self.sections = defaultdict(list)
        state, level = "others", -1
        for line in doc.splitlines():
            content = line.strip()
            if not content:
                continue
            cur = (len(line) - len(line.lstrip())) // 4
            for header in ("Args", "Returns", "Raises", "Examples"):
                if content.startswith(header + ":"):
                    state, level = header, cur
                    break
            else:
                if cur > level:
                    self.sections[state].append(content)
                    continue
                state, level = "others", -1
                self.sections[state].append(content)
        self.args = {}
        for entry in self.sections["Args"]:
            m = self._ARG_RE.search(entry)
            if m:
                self.args[m.group(1)] = m.group(2)


def _is_public(name: str) -> bool:
    return not name.startswith("_")


def _doc_findings(node, doc, path) -> Iterator[Finding]:
    """Shape rules applying to any docstring (module/class/function):
    D004-D007."""
    if doc is None:
        return
    stripped = doc.strip()
    if not stripped:
        return
    first = stripped[0]
    if not (first.isupper() or first in "\"'`[(0123456789"):
        yield Finding(path, node.lineno, "D004",
                      "docstring should start with a capital letter")
    if "\n" not in stripped and not stripped.endswith((".", "!", "?",
                                                      ":", "`", ")")):
        yield Finding(path, node.lineno, "D005",
                      "one-line docstring should end with a period")
    if "\n" in stripped and len(stripped) < ONE_LINE_MAX_CHARS:
        yield Finding(
            path, node.lineno, "D006",
            f"short docstring ({len(stripped)} chars) should be on "
            "one line")
    for cont in doc.splitlines()[1:]:
        if not cont.strip():
            continue
        indent = len(cont) - len(cont.lstrip())
        if indent % 4 != 0:
            yield Finding(path, node.lineno, "D007",
                          "docstring continuation lines should use "
                          "4-space indents")
            break


def _fn_findings(node, doc: Optional[str], path) -> Iterator[Finding]:
    """Function-body rules D008-D010 (reference ``all_args_in_doc`` /
    ``with_returns`` / ``with_raises``): only for public functions
    longer than ``MAX_UNDOCUMENTED_LINES`` that do have a docstring."""
    if doc is None:
        return
    parsed = Docstring(doc)
    a = node.args
    names = [arg.arg for arg in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
             if arg.arg not in ("self", "cls")]
    if names:
        missing = [n for n in names if n not in parsed.args]
        if missing:
            yield Finding(
                path, node.lineno, "D008",
                f"args not documented in Args section: "
                f"{', '.join(missing)}")
    # the reference inspects only TOP-LEVEL body statements
    # (``for t in node.body``) — a return/raise inside an if does not
    # trigger its W9007/W9008; match that exactly
    returns_value = any(isinstance(t, ast.Return) and t.value is not None
                        for t in node.body)
    raises = any(isinstance(t, ast.Raise) for t in node.body)
    if returns_value and not parsed.sections["Returns"]:
        yield Finding(path, node.lineno, "D009",
                      "add a Returns: section (function returns a "
                      "value)")
    if raises and not parsed.sections["Raises"]:
        yield Finding(path, node.lineno, "D010",
                      "add a Raises: section (function raises)")


def _raw_docstring(node) -> Optional[str]:
    """The UN-cleaned docstring (reference astroid ``node.doc``):
    ``ast.get_docstring`` dedents by default, which would hide the
    indentation D007 inspects."""
    return ast.get_docstring(node, clean=False)


def check_source(source: str, path: str = "<string>") -> List[Finding]:
    """All findings for one source string (D001-D010, see module doc)."""
    tree = ast.parse(source)
    findings: List[Finding] = []

    mod_doc = _raw_docstring(tree)
    if mod_doc is None:
        findings.append(Finding(path, 1, "D001",
                                "module missing docstring"))
    else:
        findings.extend(_doc_findings(tree.body[0], mod_doc, path))

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and _is_public(node.name):
            doc = _raw_docstring(node)
            if doc is None:
                findings.append(Finding(
                    path, node.lineno, "D002",
                    f"public class {node.name!r} missing docstring"))
            else:
                findings.extend(_doc_findings(node, doc, path))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_public(node.name):
            doc = _raw_docstring(node)
            n_lines = (node.end_lineno or node.lineno) - node.lineno
            if n_lines > MAX_UNDOCUMENTED_LINES and (
                    doc is None or len(doc) < MIN_DOC_CHARS):
                findings.append(Finding(
                    path, node.lineno, "D003",
                    f"public function {node.name!r} missing docstring"
                    if doc is None else
                    f"public function {node.name!r} docstring too "
                    f"short (< {MIN_DOC_CHARS} chars)"))
            elif doc is not None:
                findings.extend(_doc_findings(node, doc, path))
                if n_lines > MAX_UNDOCUMENTED_LINES:
                    findings.extend(_fn_findings(node, doc, path))
    return findings


def check_file(path: str, select=None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        found = check_source(f.read(), path)
    if select is not None:
        found = [f for f in found if f.code in select]
    return found


def main(argv=None) -> int:
    """CLI: check files/dirs; rc 0 clean, 1 findings, 2 usage error."""
    import os
    args = list(argv if argv is not None else sys.argv[1:])
    select = None
    if "--select" in args:
        i = args.index("--select")
        if i + 1 >= len(args):
            print("usage: docstring_checker.py [--select D00x,...] "
                  "<paths...>", file=sys.stderr)
            return 2
        select = set(args[i + 1].split(","))
        known = {f"D{n:03d}" for n in range(1, 11)}
        bad = select - known
        if bad:
            # a typo'd code would otherwise silently disable the rule
            print(f"unknown rule code(s): {sorted(bad)}; known: "
                  f"{sorted(known)}", file=sys.stderr)
            return 2
        del args[i:i + 2]
    findings: List[Finding] = []
    for target in args:
        if os.path.isdir(target):
            for root, _dirs, files in os.walk(target):
                findings.extend(
                    f for name in sorted(files) if name.endswith(".py")
                    for f in check_file(os.path.join(root, name),
                                        select))
        else:
            findings.extend(check_file(target, select))
    for f in findings:
        print(f)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
