"""Repo code-style tooling: the docstring checker and pfxlint.

Making ``codestyle`` a package lets the JAX-aware static-analysis
suite run as a console module from the repo root::

    python -m codestyle.pfxlint

``docstring_checker.py`` stays runnable as a plain script too
(``python codestyle/docstring_checker.py``) — nothing here imports
heavyweight dependencies at package-import time.
"""
